"""Cross-transaction signature batching onto device kernels.

The TPU answer to the reference's per-signature JCA calls inside
`SignedTransaction.checkSignaturesAreValid` (SignedTransaction.kt:96-100 →
Crypto.doVerify, Crypto.kt:473-496): many flows/transactions submit
(key, signature, content) checks concurrently; a dispatcher thread drains
them, buckets by scheme (mixed-scheme batches would diverge on device —
BASELINE.md config 2), and runs ONE batched kernel per scheme bucket.

Latency/throughput trade: a flush triggers at ``max_batch`` items or after
``max_latency_s`` from the first queued item — the p50 @ batch=1 metric pulls
against batch-size throughput (SURVEY.md §7 hard part 4).

Profiling: set CORDA_TPU_PROFILE_DIR to capture a JAX profiler trace of the
device dispatches (each batch is a named StepTraceAnnotation; view with
TensorBoard / xprof). The reference's analog is YourKit/JMX on the verifier
JVM (SURVEY.md §5 tracing).
"""
from __future__ import annotations

import os
import threading
from concurrent.futures import Future
from dataclasses import dataclass, field

from ..core.crypto import ecmath
from ..core.crypto.keys import PublicKey, curve_for_scheme, sec1_decompress
from ..core.crypto.schemes import (
    ECDSA_SECP256K1_SHA256, ECDSA_SECP256R1_SHA256, EDDSA_ED25519_SHA512)
from ..core.crypto.signatures import Crypto
from ..utils.metrics import MetricRegistry

_ED = EDDSA_ED25519_SHA512.scheme_number_id
_K1 = ECDSA_SECP256K1_SHA256.scheme_number_id
_R1 = ECDSA_SECP256R1_SHA256.scheme_number_id

_BUCKETS = {_ED: "ed25519", _K1: "secp256k1", _R1: "secp256r1"}


@dataclass
class _Pending:
    key: PublicKey
    signature: bytes
    content: bytes
    future: Future = field(default_factory=Future)


class _null_ctx:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class SignatureBatcher:
    """Accepts individual signature checks, returns Future[bool] verdicts,
    dispatches device-batched kernels per scheme from a background thread."""

    def __init__(self, max_batch: int = 512, max_latency_s: float = 0.005,
                 metrics: MetricRegistry | None = None, use_device: bool = True):
        self.max_batch = max_batch
        self.max_latency_s = max_latency_s
        self.metrics = metrics if metrics is not None else MetricRegistry()
        self.use_device = use_device
        self._lock = threading.Condition()
        self._queues: dict[str, list[_Pending]] = {
            "ed25519": [], "secp256k1": [], "secp256r1": [], "host": []}
        self._closed = False
        self._profile_dir = os.environ.get("CORDA_TPU_PROFILE_DIR")
        self._profiling = False
        self._batch_seq = 0
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="sig-batcher")
        self._thread.start()

    # -- client side ---------------------------------------------------------
    def submit(self, key: PublicKey, signature: bytes, content: bytes
               ) -> Future:
        """Future resolves to bool (valid/invalid); malformed input → False,
        matching the batch kernels' precheck semantics."""
        p = _Pending(key, signature, content)
        bucket = _BUCKETS.get(key.scheme.scheme_number_id, "host")
        if not self.use_device:
            bucket = "host"
        with self._lock:
            if self._closed:
                raise RuntimeError("SignatureBatcher is closed")
            self._queues[bucket].append(p)
            self.metrics.counter("SigBatcher.InFlight").inc()
            self._lock.notify()
        return p.future

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._lock.notify()
        self._thread.join(timeout=5)
        if self._profiling:
            import jax
            jax.profiler.stop_trace()
            self._profiling = False

    # -- dispatcher ----------------------------------------------------------
    def _run(self) -> None:
        while True:
            with self._lock:
                while not self._closed and not any(self._queues.values()):
                    self._lock.wait()
                if not any(self._queues.values()):
                    if self._closed:
                        return
                    continue
                # linger briefly to let a batch accumulate
                if (max(len(q) for q in self._queues.values()) < self.max_batch
                        and not self._closed):
                    self._lock.wait(timeout=self.max_latency_s)
                drained = {name: q[: self.max_batch]
                           for name, q in self._queues.items() if q}
                for name, items in drained.items():
                    del self._queues[name][: len(items)]
            for name, items in drained.items():
                self._dispatch(name, items)

    def _dispatch(self, bucket: str, items: list[_Pending]) -> None:
        timer = self.metrics.timer(f"SigBatcher.{bucket}.Duration")
        profile_ctx = None
        if self._profile_dir is not None and bucket != "host":
            import jax
            if not self._profiling:
                jax.profiler.start_trace(self._profile_dir)
                self._profiling = True
            self._batch_seq += 1
            profile_ctx = jax.profiler.StepTraceAnnotation(
                f"verify-{bucket}", step_num=self._batch_seq)
        try:
            with timer, (profile_ctx or _null_ctx()):
                if bucket == "ed25519":
                    verdicts = self._run_ed25519(items)
                elif bucket in ("secp256k1", "secp256r1"):
                    verdicts = self._run_ecdsa(bucket, items)
                else:
                    verdicts = []
                    for p in items:
                        try:
                            verdicts.append(
                                Crypto.is_valid(p.key, p.signature, p.content))
                        except Exception:
                            verdicts.append(False)
        except Exception as e:  # batch-level failure → fail every member
            for p in items:
                if not p.future.done():
                    p.future.set_exception(e)
            self.metrics.meter("SigBatcher.BatchFailure").mark()
            self.metrics.counter("SigBatcher.InFlight").dec(len(items))
            return
        for p, ok in zip(items, verdicts):
            p.future.set_result(bool(ok))
        self.metrics.meter("SigBatcher.Checked").mark(len(items))
        self.metrics.counter("SigBatcher.InFlight").dec(len(items))

    @staticmethod
    def _run_ed25519(items: list[_Pending]):
        from ..ops import ed25519 as ed_ops
        return ed_ops.verify_batch(
            [(p.key.encoded, p.signature, p.content) for p in items])

    @staticmethod
    def _run_ecdsa(bucket: str, items: list[_Pending]):
        from ..ops import weierstrass as wc_ops
        curve = ecmath.SECP256K1 if bucket == "secp256k1" else ecmath.SECP256R1
        kitems = []
        for p in items:
            point = sec1_decompress(curve_for_scheme(p.key.scheme), p.key.encoded)
            try:
                r, s = ecmath.ecdsa_sig_from_der(p.signature)
            except (ValueError, IndexError):
                r, s = 0, 0  # fails the kernel's range precheck → False
            kitems.append((point, p.content, r, s))
        return wc_ops.verify_batch(curve, kitems)
