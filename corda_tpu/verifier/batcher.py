"""Cross-transaction signature batching onto device kernels.

The TPU answer to the reference's per-signature JCA calls inside
`SignedTransaction.checkSignaturesAreValid` (SignedTransaction.kt:96-100 →
Crypto.doVerify, Crypto.kt:473-496): many flows/transactions submit
(key, signature, content) checks concurrently; a dispatcher thread drains
them, buckets by scheme (mixed-scheme batches would diverge on device —
BASELINE.md config 2), and runs ONE batched kernel per scheme bucket.

Pipeline shape (PR 6, continuous batching): a planner thread cuts every
dispatchable batch the per-scheme in-flight windows allow and never blocks
on one — batch N+1's host prep starts on the prep pool the moment a window
slot frees, while batch N still executes on device (the Orca-style
iteration-level scheduling discipline; the flight recorder's
``prep_overlap_pct`` is the direct measure). Device waits + future
resolution run on a separate finish pool; each in-flight slot releases at
resolution, re-waking the planner. Backpressure is per scheme
(MAX_IN_FLIGHT windows) so one slow scheme never stalls the others, and
bulk admission can be capped (``max_pending``) so producers block instead
of the queue growing without bound.

Latency/throughput trade, per latency class: ``bulk`` submissions coalesce
toward ``max_batch`` (cut at power-of-two bucket-ladder rungs so the jit
cache stays hot) with ``max_latency_s`` as the deadline; ``interactive``
submissions flush into small buckets on the much shorter
``interactive_latency_s`` deadline, with one priority in-flight slot so
bulk pressure cannot starve them — the p50 @ batch=1 metric pulls against
batch-size throughput (SURVEY.md §7 hard part 4).

Profiling: set CORDA_TPU_PROFILE_DIR to capture a JAX profiler trace of the
device dispatches (each batch is a named StepTraceAnnotation; view with
TensorBoard / xprof). The reference's analog is YourKit/JMX on the verifier
JVM (SURVEY.md §5 tracing).
"""
from __future__ import annotations

import logging
import os
import threading
import time as _time
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass

import numpy as np

from ..core.crypto import ecmath
from ..core.crypto.keys import (
    PublicKey, sec1_decompress_cached, sec1_pub_row_cached)
from ..core.crypto.schemes import (
    ECDSA_SECP256K1_SHA256, ECDSA_SECP256R1_SHA256, EDDSA_ED25519_SHA512)
from ..core.crypto.signatures import Crypto
from ..observability import get_profiler, get_tracer, jlog
from ..utils.faults import fault_point
from ..utils.metrics import MetricRegistry

_log = logging.getLogger(__name__)

_ED = EDDSA_ED25519_SHA512.scheme_number_id
_K1 = ECDSA_SECP256K1_SHA256.scheme_number_id
_R1 = ECDSA_SECP256R1_SHA256.scheme_number_id

_BUCKETS = {_ED: "ed25519", _K1: "secp256k1", _R1: "secp256r1"}

#: Admission-control latency classes: ``interactive`` submissions flush on
#: a short deadline into small buckets (a lone tx's signatures must not
#: wait behind a coalescing megabatch); ``bulk`` coalesces toward
#: full-occupancy megabatches on the ``max_latency_s`` deadline.
INTERACTIVE = "interactive"
BULK = "bulk"


class _SchemeQueue:
    """One scheme's pending work, split by latency class. ``t_first`` /
    ``t_last`` (per class) drive the deadline and stall-tick flush
    decisions in the planner — t_first is stamped on the empty→nonempty
    transition (the deadline anchor), t_last on every enqueue (a stalled
    class flushes early instead of paying the whole linger)."""

    __slots__ = ("interactive", "bulk", "t_first", "t_last")

    def __init__(self):
        self.interactive: list[_Pending] = []
        self.bulk: list[_Pending] = []
        self.t_first: dict[str, float] = {}
        self.t_last: dict[str, float] = {}

    def add(self, latency_class: str, pendings, now: float) -> None:
        lst = self.interactive if latency_class == INTERACTIVE else self.bulk
        if not lst:
            self.t_first[latency_class] = now
        self.t_last[latency_class] = now
        lst.extend(pendings)

    def drain_all(self) -> list:
        items = self.interactive + self.bulk
        self.interactive = []
        self.bulk = []
        return items

    def __len__(self) -> int:
        return len(self.interactive) + len(self.bulk)


def _tid(bctx) -> str | None:
    """Exemplar trace id for the flush's histogram samples (None when the
    batch is untraced — the histogram just skips the exemplar)."""
    return getattr(bctx, "trace_id", None)


class _Group:
    """Shared accumulator for submit_group: ONE future resolves to the
    verdict list (per-item Future objects measured ~25µs each end-to-end —
    real money at 32k-item service batches)."""

    __slots__ = ("future", "results", "remaining", "lock")

    def __init__(self, n: int):
        self.future = Future()
        self.results = [False] * n
        self.remaining = n
        self.lock = threading.Lock()


@dataclass
class _Pending:
    key: PublicKey
    signature: bytes
    content: bytes
    future: Future | None = None
    group: "_Group | None" = None
    index: int = 0
    # tracing (observability.tracing): the submitter's SpanContext, carried
    # across the dispatcher/prep/finish threads; t_enq is the wall-clock
    # enqueue time for the retroactive enqueue-wait span. Both stay at
    # their defaults when tracing is off — zero cost.
    ctx: object = None
    t_enq: float = 0.0


class _null_ctx:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class DeviceCircuitBreaker:
    """Per-scheme breaker over the device dispatch path.

    N *consecutive* device-batch failures trip CLOSED → OPEN: further
    batches of that scheme route straight to the host verify path (their
    futures still resolve — degradation, never loss). After
    ``cooldown_s`` the next batch is admitted as a HALF_OPEN probe:
    exactly one batch tries the device while the rest keep to host. A
    probe success closes the breaker; a probe failure re-opens it and
    restarts the cooldown. State and trip counts surface as registry
    gauges (``Breaker.State.<scheme>``, ``Breaker.Trips``), ``/readyz``
    degraded status, and ``breaker.*`` structured log events."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"
    _STATE_CODE = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}

    def __init__(self, scheme: str, threshold: int = 3,
                 cooldown_s: float = 5.0, clock=_time.monotonic,
                 on_trip=None):
        self.scheme = scheme
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self.clock = clock           # injectable: chaos tests step time
        self.on_trip = on_trip       # marks the registry trip meters
        self.state = self.CLOSED
        self.consecutive_failures = 0
        self.trips = 0
        self._opened_at = 0.0
        self._probe_inflight = False
        self._lock = threading.Lock()

    def state_code(self) -> int:
        return self._STATE_CODE[self.state]

    def allow(self) -> bool:
        """May the next batch try the device? OPEN past its cooldown
        admits exactly one half-open probe; everything else while not
        CLOSED routes to host."""
        with self._lock:
            if self.state == self.CLOSED:
                return True
            if self.state == self.OPEN and \
                    self.clock() - self._opened_at >= self.cooldown_s:
                self.state = self.HALF_OPEN
                self._probe_inflight = True
                jlog(_log, "breaker.half_open", scheme=self.scheme)
                return True
            if self.state == self.HALF_OPEN and not self._probe_inflight:
                self._probe_inflight = True
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            reopened = self.state != self.CLOSED
            self.state = self.CLOSED
            self.consecutive_failures = 0
            self._probe_inflight = False
        if reopened:
            jlog(_log, "breaker.close", scheme=self.scheme)

    def record_failure(self) -> None:
        with self._lock:
            self.consecutive_failures += 1
            if self.state == self.HALF_OPEN:
                # the probe failed: re-open and restart the cooldown
                self.state = self.OPEN
                self._opened_at = self.clock()
                self._probe_inflight = False
                jlog(_log, "breaker.reopen", scheme=self.scheme,
                     consecutive_failures=self.consecutive_failures)
                return
            if self.state == self.CLOSED and \
                    self.consecutive_failures >= self.threshold:
                self.state = self.OPEN
                self._opened_at = self.clock()
                self.trips += 1
                jlog(_log, "breaker.open", scheme=self.scheme,
                     consecutive_failures=self.consecutive_failures,
                     trips=self.trips)
                if self.on_trip is not None:
                    self.on_trip(self.scheme)

    def status(self) -> dict:
        return {"state": self.state, "trips": self.trips,
                "consecutive_failures": self.consecutive_failures}


class SignatureBatcher:
    """Accepts individual signature checks, returns Future[bool] verdicts,
    dispatches device-batched kernels per scheme from a background thread.

    Batch-size policy (VERDICT r2 #1): the cap defaults to the kernels'
    measured throughput sweet spot (32k; BASELINE.md "the fixed ~140 ms
    dispatch floor amortizes past batch ~8k") and the drain adapts to load —
    kernels pad to power-of-two buckets so variable batch sizes compile once
    per bucket, not per length. Batches *below* ``host_crossover`` route to
    the host verify path instead: with a ~140 ms device dispatch floor and
    ~2k verifies/s on one host core, a batch under ~200 items finishes on
    host before the device kernel would even launch — this is what makes
    p50 @ batch=1 milliseconds instead of the dispatch floor. Below the
    crossover the dispatcher also skips the linger wait, so a lone submit
    is not taxed ``max_latency_s`` for a batch that was never coming."""

    #: Prep-pool width: one worker per device scheme, so a mixed drain preps
    #: ed25519 + k1 + r1 concurrently. The heavy prep (sm_*_prep, hashing,
    #: numpy packing) releases the GIL in C, so the workers genuinely
    #: overlap; same width for the finish pool (device waits are
    #: GIL-releasing too).
    PREP_WORKERS = 3

    #: Default bucket-ladder floor: below this the kernels' pow2 padding
    #: already keeps the shape set small, and the host crossover eats most
    #: sub-floor batches anyway.
    LADDER_FLOOR = 256

    def __init__(self, max_batch: int = 32768, max_latency_s: float = 0.005,
                 metrics: MetricRegistry | None = None, use_device: bool = True,
                 host_crossover: int = 192, mesh=None, device=None,
                 breaker_threshold: int = 3, breaker_cooldown_s: float = 5.0,
                 breaker_clock=_time.monotonic,
                 interactive_latency_s: float = 0.002,
                 interactive_batch: int = 1024,
                 bucket_ladder=None, max_pending: int | None = None):
        self.max_batch = max_batch
        self.max_latency_s = max_latency_s
        self.metrics = metrics if metrics is not None else MetricRegistry()
        self.use_device = use_device
        self.host_crossover = host_crossover
        # latency classes (admission control): interactive flushes on its
        # own short deadline in small buckets with one priority in-flight
        # slot; bulk coalesces toward max_batch on max_latency_s
        self.interactive_latency_s = interactive_latency_s
        self.interactive_batch = interactive_batch
        # bulk admission cap: enqueues block while this many bulk items are
        # queued (interactive is always admitted) — backpressure lands on
        # the bulk producers instead of growing the queue without bound
        self.max_pending = max_pending
        # degradation-ladder state (verifier/controller.py): each rung is
        # reversible, saving whatever it overrides so revert is exact
        self._shed_active = False
        self._saved_max_pending: int | None = None
        self._ladder_shrunk = False
        self._saved_ladders: tuple | None = None
        self._force_host_interactive = False
        # shape-bucketed batch sizes: bulk drains are cut at power-of-two
        # ladder rungs so the jit cache sees a fixed shape set across
        # varying arrival rates. None → default ladder for every scheme; a
        # sequence → that ladder for every scheme; a dict → per-scheme
        # (see ladder_from_occupancy for tuning from flight-recorder stats)
        self._default_ladder = self._pow2_ladder(self.LADDER_FLOOR, max_batch)
        if bucket_ladder is None:
            self.bucket_ladder: dict[str, tuple] = {}
        elif isinstance(bucket_ladder, dict):
            self.bucket_ladder = {k: tuple(v) for k, v in bucket_ladder.items()}
        else:
            self._default_ladder = tuple(bucket_ladder)
            self.bucket_ladder = {}
        # a jax.sharding.Mesh shards every device batch over the local chips
        # (shard_map dp axis) — one node's batcher drives the whole slice
        self.mesh = mesh
        # device-shard pinning (verifier fleet): a single jax.Device this
        # batcher's dispatches run on, so N worker processes/batchers on one
        # host each own a disjoint chip. Dispatch wraps jax.default_device
        # (thread-local config — safe on the prep pool); mutually exclusive
        # with mesh, which already owns explicit devices.
        self.device = device
        if mesh is not None and device is not None:
            raise ValueError("pass mesh= or device=, not both")
        self._lock = threading.Condition()
        self._queues: dict[str, _SchemeQueue] = {
            "ed25519": _SchemeQueue(), "secp256k1": _SchemeQueue(),
            "secp256r1": _SchemeQueue(), "host": _SchemeQueue()}
        # per-scheme in-flight batch counts (prep start → resolution): the
        # planner stops cutting plans for a scheme at its window, and each
        # plan carries an idempotent release that decrements + re-wakes the
        # planner — continuous dispatch, no drain barrier.
        self._inflight_n: dict[str, int] = {name: 0 for name in self._queues}
        self._closed = False
        self._prep_pool: ThreadPoolExecutor | None = None
        self._finish_pool: ThreadPoolExecutor | None = None
        self._pool_lock = threading.Lock()
        self._prep_active = 0
        self._profile_dir = os.environ.get("CORDA_TPU_PROFILE_DIR")
        self._profiling = False
        self._batch_seq = 0
        self._profile_lock = threading.Lock()
        for name in self._queues:
            # per-scheme observability: queue depth (pending drain) and
            # in-flight window occupancy (batches between prep + resolve)
            self.metrics.gauge(f"SigBatcher.{name}.QueueDepth",
                               lambda n=name: len(self._queues[n]))
            self.metrics.gauge(f"SigBatcher.{name}.InFlight",
                               lambda n=name: self._inflight_n[n])
        # device circuit breakers, one per device scheme: N consecutive
        # dispatch failures degrade that scheme to host verification (the
        # futures still resolve); a half-open probe restores it. Created
        # even with use_device=False so the gauge families are always
        # present — they just never trip.
        self.metrics.meter("Breaker.Trips")
        self._breakers: dict[str, DeviceCircuitBreaker] = {}
        for name in ("ed25519", "secp256k1", "secp256r1"):
            self._breakers[name] = DeviceCircuitBreaker(
                name, threshold=breaker_threshold,
                cooldown_s=breaker_cooldown_s, clock=breaker_clock,
                on_trip=self._on_breaker_trip)
            self.metrics.gauge(
                f"Breaker.State.{name}",
                lambda n=name: self._breakers[n].state_code())
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="sig-batcher")
        self._thread.start()

    def _on_breaker_trip(self, scheme: str) -> None:
        self.metrics.meter("Breaker.Trips").mark()
        self.metrics.meter(f"Breaker.Trips.{scheme}").mark()

    def breaker_status(self) -> dict:
        """Per-scheme breaker state for /readyz and bench assertions."""
        return {name: b.status() for name, b in self._breakers.items()}

    def queue_depths(self) -> dict:
        """Per-scheme pending depth (signatures queued, not yet planned) —
        the load snapshot the OOP worker ships to the node's router in its
        WorkerLoadReport (same numbers as the SigBatcher.<name>.QueueDepth
        gauges, one lock round)."""
        with self._lock:
            return {name: len(q) for name, q in self._queues.items()}

    # -- bucket ladder -------------------------------------------------------
    @staticmethod
    def _pow2_ladder(floor: int, cap: int) -> tuple:
        """Power-of-two rungs from ``floor`` up to ``cap`` (cap included
        even when it is not a power of two — it is the one extra shape the
        megabatch path already compiles)."""
        if cap <= floor:
            return (cap,)
        rungs = []
        r = floor
        while r <= cap:
            rungs.append(r)
            r *= 2
        if rungs[-1] != cap:
            rungs.append(cap)
        return tuple(rungs)

    def _ladder_for(self, bucket: str) -> tuple:
        return self.bucket_ladder.get(bucket, self._default_ladder)

    def _ladder_cut(self, bucket: str, depth: int) -> int:
        """Bulk drain size for ``depth`` queued items: the largest ladder
        rung that fits, so steady-state flushes recur on a fixed shape set
        and the jit cache stays hot. Sub-floor tails dispatch at raw depth
        — the kernels pad those to power-of-two buckets, so the compiled
        shape set stays bounded either way."""
        cut = 0
        for rung in self._ladder_for(bucket):
            if rung <= depth:
                cut = rung
        if cut == 0:
            cut = depth
        return min(cut, self.max_batch, depth)

    # -- degradation ladder hooks (verifier/controller.py) -------------------
    def shed_bulk(self, on: bool, cap: int | None = None) -> None:
        """Controller rung 1: clamp bulk admission hard. Bulk producers
        block at a small cap (default ``interactive_batch``) so offered
        throughput load backs off while interactive traffic — always
        admitted — keeps its latency. Reversal restores the configured
        ``max_pending`` exactly (including None = uncapped)."""
        with self._lock:
            if on and not self._shed_active:
                self._shed_active = True
                self._saved_max_pending = self.max_pending
                self.max_pending = (cap if cap is not None
                                    else self.interactive_batch)
            elif not on and self._shed_active:
                self._shed_active = False
                self.max_pending = self._saved_max_pending
                self._saved_max_pending = None
            self._lock.notify_all()

    def shrink_ladder(self, on: bool) -> None:
        """Controller rung 2: collapse the bulk batch ladder to its floor
        so drains cut small, low-latency batches — queueing delay behind a
        coalescing megabatch is what burns the latency SLO under stress.
        The pre-shrink ladders (default + per-scheme) are restored on
        reversal."""
        with self._lock:
            if on and not self._ladder_shrunk:
                self._ladder_shrunk = True
                self._saved_ladders = (self._default_ladder,
                                       self.bucket_ladder)
                self._default_ladder = (min(self.LADDER_FLOOR,
                                            self.max_batch),)
                self.bucket_ladder = {}
            elif not on and self._ladder_shrunk:
                self._ladder_shrunk = False
                self._default_ladder, self.bucket_ladder = \
                    self._saved_ladders
                self._saved_ladders = None
            self._lock.notify_all()

    def route_interactive_host(self, on: bool) -> None:
        """Controller rung 3 (last resort): route interactive-class
        submissions to the host bucket — a few host-verified signatures
        beat queueing behind a saturated device path. Bulk keeps the
        device."""
        self._force_host_interactive = bool(on)

    def degradation_status(self) -> dict:
        """Which rungs are applied (fleet_status / readyz diagnostics)."""
        return {"bulk_shed": self._shed_active,
                "ladder_shrunk": self._ladder_shrunk,
                "interactive_host": self._force_host_interactive,
                "max_pending": self.max_pending}

    @classmethod
    def ladder_from_occupancy(cls, profiler=None, max_batch: int = 32768,
                              min_floor: int | None = None) -> dict:
        """Per-scheme bucket ladders tuned from the flight recorder's
        occupancy stats: the floor doubles toward each scheme's observed
        mean live batch (one rung of headroom below it), so a scheme that
        sustains megabatches skips the tiny rungs while a trickle-fed one
        keeps them. Feed the result to ``SignatureBatcher(bucket_ladder=)``
        on the next (re)start."""
        if profiler is None:
            profiler = get_profiler()
        floor0 = min_floor if min_floor is not None else cls.LADDER_FLOOR
        ladders = {}
        for scheme, mean_live in profiler.occupancy_mean_live().items():
            floor = floor0
            while floor * 4 <= mean_live and floor * 2 <= max_batch:
                floor *= 2
            ladders[scheme] = cls._pow2_ladder(floor, max_batch)
        return ladders

    # -- client side ---------------------------------------------------------
    def submit(self, key: PublicKey, signature: bytes, content: bytes,
               ctx=None, latency_class: str = INTERACTIVE) -> Future:
        """Future resolves to bool (valid/invalid); malformed input → False,
        matching the batch kernels' precheck semantics. Single submits
        default to the interactive latency class: a lone check flushes on
        the short deadline instead of lingering behind a coalescing
        megabatch."""
        return self.submit_many([(key, signature, content)], ctx=ctx,
                                latency_class=latency_class)[0]

    def submit_many(self, checks, ctx=None,
                    latency_class: str = BULK) -> list[Future]:
        """Bulk submission: one lock round for a whole transaction's (or
        ledger's) signature set — the per-item lock churn matters at the
        32k-batch scale the service path runs. ``ctx`` is the submitter's
        SpanContext: the flushed batch's spans join that trace."""
        pendings = [_Pending(key, sig, content, future=Future())
                    for key, sig, content in checks]
        self._stamp_trace(pendings, ctx)
        self._enqueue(pendings, latency_class)
        return [p.future for p in pendings]

    def submit_group(self, checks, ctx=None,
                     latency_class: str = BULK) -> Future:
        """Submit a set of checks resolved by ONE future of verdict bools
        (in submission order) — the bulk interface for callers that consume
        whole batches (the service's verify_signed, the OOP worker, service
        benchmarks). ``latency_class="interactive"`` puts the group on the
        short-deadline path (service.verify_signed uses it: one tx's few
        signatures are latency-bound, not throughput-bound)."""
        group = _Group(len(checks))
        pendings = [_Pending(key, sig, content, group=group, index=i)
                    for i, (key, sig, content) in enumerate(checks)]
        self._stamp_trace(pendings, ctx)
        self._enqueue(pendings, latency_class)
        if not pendings:
            group.future.set_result([])
        return group.future

    @staticmethod
    def _stamp_trace(pendings, ctx) -> None:
        if ctx is None:     # tracing off, or an untraced caller
            return
        now = _time.time()
        for p in pendings:
            p.ctx = ctx
            p.t_enq = now

    def _enqueue(self, pendings: list[_Pending],
                 latency_class: str = BULK) -> None:
        # bucket lookups happen OUTSIDE the condition lock: a 32k-item
        # submission must not hold the dispatcher up for the whole scan
        force_host = (self._force_host_interactive
                      and latency_class == INTERACTIVE)
        routed: dict[str, list[_Pending]] = {}
        for p in pendings:
            bucket = ("host" if not self.use_device or force_host
                      else _BUCKETS.get(p.key.scheme.scheme_number_id, "host"))
            routed.setdefault(bucket, []).append(p)
        with self._lock:
            if self._closed:
                raise RuntimeError("SignatureBatcher is closed")
            if self.max_pending is not None and latency_class == BULK:
                # admission control: bulk producers block at the cap
                # (interactive is always admitted — its whole point is
                # bounded latency under bulk pressure). The planner's
                # drains notify this wait as depth comes down.
                blocked_t0 = _time.time()
                blocked = False
                while (not self._closed
                       and sum(len(q.bulk) for q in self._queues.values())
                       >= self.max_pending):
                    blocked = True
                    self._lock.wait(timeout=0.1)
                if self._closed:
                    raise RuntimeError("SignatureBatcher is closed")
                if blocked:
                    # wait-state span: admission blocked at the bulk cap.
                    # One span per submission, parented to the (shared)
                    # caller context stamped on the wave's pendings.
                    ctx = next((p.ctx for p in pendings
                                if p.ctx is not None), None)
                    if ctx is not None:
                        now = _time.time()
                        get_tracer().record(
                            "wait.verifier_admission", parent=ctx,
                            start_s=blocked_t0, duration_s=now - blocked_t0,
                            wait_kind="verifier.admission",
                            n_sigs=len(pendings))
            now = _time.monotonic()
            for bucket, ps in routed.items():
                self._queues[bucket].add(latency_class, ps, now)
            self.metrics.counter("SigBatcher.InFlight").inc(len(pendings))
            self._lock.notify_all()

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._lock.notify_all()
        # the planner drains its queues AND waits out every in-flight plan
        # before exiting; the pool shutdowns then reap the workers — prep
        # first (prep tasks submit finish tasks), then finish.
        self._thread.join(timeout=60)
        if self._prep_pool is not None:
            self._prep_pool.shutdown(wait=True)
        if self._finish_pool is not None:
            self._finish_pool.shutdown(wait=True)
        if self._profiling:
            import jax
            jax.profiler.stop_trace()
            self._profiling = False

    # -- dispatcher (continuous-batching planner) ----------------------------
    def _run(self) -> None:
        # The planner thread never blocks on a batch: each pass cuts every
        # plan the in-flight windows allow (interactive first, then bulk at
        # ladder rungs), hands them to the prep pool, and goes back to
        # sleep until the nearest class deadline or the next enqueue /
        # release notification. Batch N+1's host prep therefore starts the
        # moment a window slot frees — while batch N still executes on
        # device — instead of after a drain barrier.
        while True:
            with self._lock:
                now = _time.monotonic()
                plans, wake = self._plan_locked(now)
                if not plans:
                    if (self._closed
                            and not any(self._queues.values())
                            and not any(self._inflight_n.values())):
                        break
                    timeout = None if wake is None else max(0.0, wake - now)
                    self._lock.wait(timeout=timeout)
                    continue
            for bucket, items, reason, release in plans:
                self._submit_flush(bucket, items, reason, release)

    def _plan_locked(self, now: float):
        """Cut every dispatchable plan from the queues (CALLER HOLDS THE
        LOCK). Returns (plans, wake): plans are (bucket, items, reason,
        release) tuples ready for the prep pool; wake is the earliest
        future deadline among the classes that are not ready yet (None
        when nothing is waiting on time)."""
        plans = []
        wake = None
        for name, q in self._queues.items():
            if not (q.interactive or q.bulk):
                continue
            window = self.MAX_IN_FLIGHT if name != "host" \
                else self.MAX_IN_FLIGHT + 1
            if name == "host" or len(q) < self.host_crossover:
                # host route (below the crossover both classes merge — the
                # host loop has no shape or occupancy stake, and waiting
                # would add pure latency: the p50@1 case)
                if self._inflight_n[name] < self.MAX_IN_FLIGHT + 1:
                    reason = "close" if self._closed else (
                        "host" if name == "host" else "small_batch")
                    plans.append(self._make_plan(name, q.drain_all(), reason))
                continue
            # interactive: short deadline, small buckets, ONE priority slot
            # past the bulk window so bulk pressure cannot starve it
            if q.interactive:
                ready, reason, deadline = self._class_ready(
                    len(q.interactive), q.t_first[INTERACTIVE],
                    q.t_last[INTERACTIVE], now,
                    self.interactive_batch, self.interactive_latency_s)
                if ready:
                    while (q.interactive and self._inflight_n[name]
                           < self.MAX_IN_FLIGHT + 1):
                        cut = min(len(q.interactive), self.interactive_batch)
                        items = q.interactive[:cut]
                        del q.interactive[:cut]
                        plans.append(self._make_plan(name, items, reason))
                elif wake is None or deadline < wake:
                    wake = deadline
            # bulk: coalesce toward max_batch, cut at ladder rungs so the
            # jit cache re-sees the same shapes across arrival rates
            if q.bulk:
                ready, reason, deadline = self._class_ready(
                    len(q.bulk), q.t_first[BULK], q.t_last[BULK], now,
                    self.max_batch, self.max_latency_s)
                if ready:
                    while q.bulk and self._inflight_n[name] < window:
                        cut = self._ladder_cut(name, len(q.bulk))
                        items = q.bulk[:cut]
                        del q.bulk[:cut]
                        plans.append(self._make_plan(name, items, reason))
                elif wake is None or deadline < wake:
                    wake = deadline
        if plans:
            # queue depth dropped: re-admit blocked bulk producers
            self._lock.notify_all()
        return plans, wake

    def _class_ready(self, depth: int, t_first: float, t_last: float,
                     now: float, cap: int, latency: float):
        """(ready, reason, deadline) for one latency class: flush at the
        cap, at the class deadline (t_first + latency), or one stall tick
        after the last arrival — an atomic burst stops paying the whole
        linger while a trickling burst keeps coalescing (VERDICT r4 #7)."""
        if self._closed:
            return True, "close", None
        if depth >= cap:
            return True, "max_batch", None
        hard = t_first + latency
        stall = t_last + latency / 5
        if now >= hard:
            return True, "deadline", None
        if now >= stall:
            return True, "stalled", None
        return False, None, min(hard, stall)

    def _make_plan(self, bucket: str, items: list[_Pending], reason: str):
        """Claim an in-flight slot for one cut batch (CALLER HOLDS THE
        LOCK) and build its idempotent release — the continuous-batching
        seam: the slot frees (and the planner re-wakes) the moment the
        batch RESOLVES, from whichever pool thread got there, never from a
        planner-side blocking wait."""
        self._inflight_n[bucket] += 1
        released = [False]

        def release(_f=None):
            with self._lock:
                if released[0]:
                    return
                released[0] = True
                self._inflight_n[bucket] -= 1
                self._lock.notify_all()

        return bucket, items, reason, release

    def _submit_flush(self, bucket: str, items: list[_Pending],
                      reason: str, release) -> None:
        """Hand one planned batch to the prep pool. Never blocks: window
        accounting already happened in the planner, so the only wait left
        anywhere is pool scheduling."""
        if self._prep_pool is None:
            self._prep_pool = ThreadPoolExecutor(
                max_workers=self.PREP_WORKERS,
                thread_name_prefix="sig-batcher-prep")
        try:
            self._prep_pool.submit(
                self._flush_slot, bucket, items, reason, release)
        except RuntimeError:
            # pool already shut down (close() raced a long drain): flush
            # inline so no queued caller's future is dropped
            inner = self._flush_slot(bucket, items, reason, release)
            if inner is not None:
                inner.result()

    def _flush_slot(self, bucket: str, items: list[_Pending], reason: str,
                    release):
        """_flush under slot accounting: the in-flight slot releases when
        the batch fully resolves (inline for host routes, at the finish
        future for pipelined device batches), and a prep/finish crash
        fails the batch's futures instead of leaking them — zero lost
        futures even through a breaker trip mid-pipeline."""
        try:
            inner = self._flush(bucket, items, reason)
        except BaseException as exc:
            _log.exception("signature batch prep/finish failed")
            self.metrics.meter("SigBatcher.BatchFailure").mark()
            self._fail_items(items, exc)
            release()
            return None
        if inner is None:
            release()
        else:
            inner.add_done_callback(release)
        return inner

    def _fail_items(self, items: list[_Pending], exc: BaseException) -> None:
        """Resolve a crashed batch's futures with the failure. Futures that
        already resolved (the crash hit after _resolve) are left alone."""
        groups = {}
        for p in items:
            if p.group is not None:
                groups[id(p.group)] = p.group
            else:
                try:
                    p.future.set_exception(exc)
                except Exception:
                    pass
        for g in groups.values():
            try:
                g.future.set_exception(exc)
            except Exception:
                pass
        self.metrics.counter("SigBatcher.InFlight").dec(len(items))

    def _flush(self, bucket: str, items: list[_Pending], reason: str):
        """Route one drained bucket: host loop below the crossover, device
        kernels above. RUNS ON A PREP-POOL WORKER, so a mixed drain's
        buckets prep and dispatch concurrently. Returns the finish-stage
        Future for pipelined device batches (None when the batch resolved
        inline). Records the per-flush histogram + trace spans."""
        gauge = self.metrics.settable_gauge("SigBatcher.PrepActive")
        with self._pool_lock:
            self._prep_active += 1
            gauge.set(self._prep_active)
        try:
            self.metrics.histogram("verifier_batch_size").update(len(items))
            tracer = get_tracer()
            bctx = self._trace_flush(tracer, bucket, items, reason) \
                if tracer.enabled else None
            jlog(_log, "batcher.flush", ctx=bctx, bucket=bucket,
                 batch_size=len(items), flush_reason=reason)
            if bucket == "host" or len(items) < self.host_crossover:
                if bucket != "host":
                    self.metrics.meter("SigBatcher.HostRouted").mark(
                        len(items))
                t0 = _time.perf_counter()
                with tracer.span("batcher.dispatch", parent=bctx,
                                 bucket=bucket, batch_size=len(items),
                                 route="host"):
                    verdicts = self._run_host(items)
                self.metrics.histogram("verifier_dispatch_seconds").update(
                    _time.perf_counter() - t0, trace_id=_tid(bctx))
                self._resolve("host", items, verdicts, bctx)
                return None
            breaker = self._breakers[bucket]
            if not breaker.allow():
                # breaker open: degrade THIS scheme to host verification —
                # every future still resolves, the device just isn't tried.
                # Occupancy stats still update (a host batch is 100% live —
                # no padding), so degraded mode keeps the per-scheme
                # QueueDepth/InFlight gauges and the flight recorder's
                # occupancy surface fresh instead of frozen at the last
                # device batch.
                self.metrics.meter("SigBatcher.BreakerRouted").mark(
                    len(items))
                get_profiler().record_occupancy(bucket, len(items),
                                                len(items))
                t0 = _time.perf_counter()
                with tracer.span("batcher.dispatch", parent=bctx,
                                 bucket=bucket, batch_size=len(items),
                                 route="breaker_open"):
                    verdicts = self._run_host(items)
                self.metrics.histogram("verifier_dispatch_seconds").update(
                    _time.perf_counter() - t0, trace_id=_tid(bctx))
                self._resolve(bucket, items, verdicts, bctx)
                return None
            return self._dispatch_device(bucket, items, reason, bctx)
        finally:
            with self._pool_lock:
                self._prep_active -= 1
                gauge.set(self._prep_active)

    #: Per-flush cap on retroactive enqueue-wait spans: a fully-traced 32k
    #: batch must not turn one flush into 32k ring inserts.
    MAX_WAIT_SPANS = 64

    def _trace_flush(self, tracer, bucket, items, reason):
        """Record the flush span (+ capped per-item enqueue-wait spans) and
        return its context — the parent for dispatch/wait/resolve spans.
        A mixed batch carries many traces; the flush span joins the FIRST
        traced submitter's trace and tags how many others rode along."""
        now = _time.time()
        first_ctx = None
        traced = 0
        for p in items:
            if p.ctx is None:
                continue
            traced += 1
            if first_ctx is None:
                first_ctx = p.ctx
            if traced <= self.MAX_WAIT_SPANS:
                tracer.record("batcher.enqueue_wait", parent=p.ctx,
                              start_s=p.t_enq,
                              duration_s=max(0.0, now - p.t_enq),
                              bucket=bucket)
        return tracer.record("batcher.flush", parent=first_ctx, start_s=now,
                             bucket=bucket, batch_size=len(items),
                             flush_reason=reason, n_traced=traced)

    #: Max device batches in flight PER SCHEME: the one just launched plus
    #: two awaiting their results. A/B on v5e (3 runs each, 32k batches):
    #: 3-deep 26.6-29.4k/s; strict 2-deep (gate before launch)
    #: 21.0-22.7k/s; 1-deep 18.8-22.8k/s. Worst-case extra device residency
    #: is one batch's buffers (~tens of MB at 32k) — noise against HBM.
    MAX_IN_FLIGHT = 3

    def _profile_step(self, bucket: str):
        """StepTraceAnnotation for one device dispatch (None when profiling
        is off). The start-once + sequence state needs a lock now that
        dispatches run concurrently on the prep pool."""
        if self._profile_dir is None:
            return None
        import jax
        with self._profile_lock:
            if not self._profiling:
                jax.profiler.start_trace(self._profile_dir)
                self._profiling = True
            self._batch_seq += 1
            seq = self._batch_seq
        return jax.profiler.StepTraceAnnotation(f"verify-{bucket}",
                                                step_num=seq)

    def _dispatch_device(self, bucket: str, items: list[_Pending],
                         reason: str = "full", bctx=None):
        """Kernel prep + async launch for one scheme bucket; returns the
        finish-stage Future (None when resolved here). The try below covers
        ONLY kernel prep/dispatch: a failure there falls back to host
        verdicts, but a failure inside _resolve must propagate — re-running
        _resolve on the same items would double-resolve group members
        (remaining underflow, double set_result)."""
        profile_ctx = self._profile_step(bucket)
        tracer = get_tracer()
        dspan = tracer.span("batcher.dispatch", parent=bctx, bucket=bucket,
                            batch_size=len(items), route="device",
                            flush_reason=reason)
        t_prep = _time.perf_counter()
        mesh_verdicts = None
        breaker = self._breakers[bucket]
        if self.device is not None:
            # device-shard pin: uncommitted (numpy) kernel inputs follow the
            # default device, so wrapping the launch places this batch on
            # the worker's own chip (jax.default_device is thread-local —
            # concurrent prep-pool dispatches don't leak across batchers)
            import jax
            pin_ctx = jax.default_device(self.device)
        else:
            pin_ctx = _null_ctx()
        try:
            with self.metrics.timer(f"SigBatcher.{bucket}.Prep"), \
                    (profile_ctx or _null_ctx()), pin_ctx:
                # chaos seam: a "raise" rule here exercises exactly the
                # fallback + breaker path a real kernel failure would
                fault_point("batcher.device_dispatch", detail=bucket)
                if self.mesh is not None:
                    # mesh path resolves immediately (sharded helpers force)
                    if bucket == "ed25519":
                        mesh_verdicts = self._run_ed25519(items)
                    else:
                        mesh_verdicts = self._run_ecdsa(bucket, items)
                else:
                    # host prep HERE — overlaps other schemes' preps and
                    # the finish pool's device waits
                    if bucket == "ed25519":
                        pending, finish = self._start_ed25519(items)
                    else:
                        pending, finish = self._start_ecdsa(bucket, items)
        except Exception:
            # batch-level failure (kernel/compile/transfer): fall back to
            # per-item host verification so one malformed member — or a
            # transient device error — cannot fail unrelated transactions'
            # futures (VERDICT r2 weak #9)
            self.metrics.meter("SigBatcher.BatchFailure").mark()
            breaker.record_failure()
            dspan.set_tag("fallback", "host")
            dspan.finish()
            self._resolve(bucket, items, self._run_host(items), bctx)
            return None
        if self.mesh is not None:
            breaker.record_success()
            self._mark_device(items)
            self.metrics.histogram("verifier_dispatch_seconds").update(
                _time.perf_counter() - t_prep, trace_id=_tid(bctx))
            dspan.set_tag("mesh", True)
            dspan.finish()
            self._resolve(bucket, items, mesh_verdicts, bctx)
            return None
        t_end = _time.perf_counter()
        # feed the flight recorder's pipeline view: this prep busy interval
        # intersected against the finish pool's device-wait intervals
        get_profiler().overlap.add_prep(t_prep, t_end)
        self.metrics.histogram("verifier_prep_seconds").update(
            t_end - t_prep, trace_id=_tid(bctx))
        dspan.finish()
        # pipelined: the finish pool blocks on the device result (a
        # GIL-releasing wait) and resolves the futures; this prep worker is
        # immediately free for the next batch
        return self._submit_finish(bucket, items, pending, finish, bctx)

    def _submit_finish(self, bucket, items, pending, finish, bctx):
        if self._finish_pool is None:
            with self._pool_lock:        # prep workers race the first batch
                if self._finish_pool is None:
                    self._finish_pool = ThreadPoolExecutor(
                        max_workers=self.PREP_WORKERS,
                        thread_name_prefix="sig-batcher-finish")
        try:
            return self._finish_pool.submit(
                self._finish_one, bucket, items, pending, finish, bctx)
        except RuntimeError:
            # pool already shut down (close() raced a long drain)
            self._finish_one(bucket, items, pending, finish, bctx)
            return None

    def _finish_one(self, bucket, items, pending, finish, bctx=None) -> None:
        # bctx crossed from the prep thread via the executor args —
        # the explicit-propagation seam the tracer tests pin down
        wspan = get_tracer().span("batcher.device_wait", parent=bctx,
                                  bucket=bucket, batch_size=len(items))
        t0 = _time.perf_counter()
        try:
            with wspan, self.metrics.timer(f"SigBatcher.{bucket}.Duration"):
                verdicts = finish(pending)
            t_end = _time.perf_counter()
            self._breakers[bucket].record_success()
            self._mark_device(items)
            get_profiler().overlap.add_device(t0, t_end)
            self.metrics.histogram("verifier_dispatch_seconds").update(
                t_end - t0, trace_id=_tid(bctx))
        except Exception:
            self.metrics.meter("SigBatcher.BatchFailure").mark()
            self._breakers[bucket].record_failure()
            verdicts = self._run_host(items)
        self._resolve(bucket, items, verdicts, bctx)

    def _mark_device(self, items) -> None:
        self.metrics.meter("SigBatcher.DeviceBatches").mark()
        self.metrics.meter("SigBatcher.DeviceChecked").mark(len(items))

    def _resolve(self, bucket: str, items: list[_Pending], verdicts,
                 bctx=None) -> None:
        tracer = get_tracer()
        t_wall = _time.time() if tracer.enabled else 0.0
        t0 = _time.perf_counter()
        # Group fan-in, batched: each result slot is written by exactly one
        # flush (disjoint indices), so the writes need no lock — only the
        # shared `remaining` count does, and that is taken ONCE per group
        # per flush (it was once per ITEM; a 32k single-group flush paid
        # 32k acquires).
        group_counts: dict[int, list] = {}
        for p, ok in zip(items, verdicts):
            if p.group is not None:
                g = p.group
                g.results[p.index] = bool(ok)
                entry = group_counts.get(id(g))
                if entry is None:
                    group_counts[id(g)] = [g, 1]
                else:
                    entry[1] += 1
            else:
                try:
                    p.future.set_result(bool(ok))
                except Exception:
                    pass   # caller cancelled its future; verdict dropped
        done_groups = []
        for g, n_done in group_counts.values():
            with g.lock:
                g.remaining -= n_done
                if g.remaining == 0:
                    done_groups.append(g)
        for g in done_groups:
            try:
                g.future.set_result(g.results)
            except Exception:
                pass
        self.metrics.meter("SigBatcher.Checked").mark(len(items))
        self.metrics.counter("SigBatcher.InFlight").dec(len(items))
        dt = _time.perf_counter() - t0
        self.metrics.histogram("verifier_finish_seconds").update(
            dt, trace_id=_tid(bctx))
        if tracer.enabled:
            tracer.record("batcher.resolve", parent=bctx, start_s=t_wall,
                          duration_s=dt, bucket=bucket,
                          batch_size=len(items))

    @staticmethod
    def _run_host(items: list[_Pending]) -> list[bool]:
        verdicts = []
        for p in items:
            try:
                verdicts.append(Crypto.is_valid(p.key, p.signature, p.content))
            except Exception:
                verdicts.append(False)
        return verdicts

    def _run_ed25519(self, items: list[_Pending]):
        triples = [(p.key.encoded, p.signature, p.content) for p in items]
        if self.mesh is not None:
            from ..parallel import sharded_verify_batch_ed25519
            return sharded_verify_batch_ed25519(self.mesh, triples)
        from ..ops import ed25519 as ed_ops
        return ed_ops.verify_batch(triples)

    @staticmethod
    def _start_ed25519(items: list[_Pending]):
        from ..ops import ed25519 as ed_ops
        pending = ed_ops.verify_batch_async(
            [(p.key.encoded, p.signature, p.content) for p in items])
        return pending, ed_ops.finish_batch

    @staticmethod
    def _ecdsa_kernel_items(curve, items: list[_Pending]):
        kitems = []
        for p in items:
            # per-item isolation: ANY malformed member becomes a False
            # verdict for that member alone, never a batch failure
            try:
                point = sec1_decompress_cached(curve, p.key.encoded)
                r, s = ecmath.ecdsa_sig_from_der(p.signature)
            except Exception:
                point, r, s = None, 0, 0  # fails the range precheck → False
            kitems.append((point, p.content, r, s))
        return kitems

    @staticmethod
    def _ecdsa_words(curve, items: list[_Pending]):
        """Cached + vectorized ECDSA kernel prep: per-signer pub rows from
        keys.sec1_pub_row_cached (the Weierstrass sibling of the Ed25519
        kernel's _signer_row cache), ONE batched DER parse
        (scalarprep.ecdsa_sigs_to_words), digests packed straight into the
        native preps' LE u64 word rows — replacing the per-item decompress
        + DER parse + bigint to_bytes loop of _ecdsa_kernel_items.
        Per-item isolation is preserved: any malformed member gets r := 0,
        which the native range precheck rejects into a False verdict for
        that member alone."""
        import hashlib
        from ..ops import scalarprep as sp
        r_words, s_words, ok = sp.ecdsa_sigs_to_words(
            [p.signature for p in items])
        pub_words = np.zeros((len(items), 8), dtype=np.uint64)
        for i, p in enumerate(items):
            row = sec1_pub_row_cached(curve, p.key.encoded)
            if row is None:
                ok[i] = False
            else:
                pub_words[i] = row
        r_words[~ok] = 0     # force the range precheck to reject
        e_words = sp.digests_to_words(
            [hashlib.sha256(p.content).digest() for p in items], 4)
        return e_words, r_words, s_words, pub_words

    def _run_ecdsa(self, bucket: str, items: list[_Pending]):
        from ..ops import weierstrass as wc_ops
        curve = ecmath.SECP256K1 if bucket == "secp256k1" else ecmath.SECP256R1
        if self.mesh is not None and bucket == "secp256k1":
            from ..parallel import (
                sharded_verify_batch_secp256k1,
                sharded_verify_batch_secp256k1_words)
            if wc_ops.words_prep_available(curve):
                return sharded_verify_batch_secp256k1_words(
                    self.mesh, *self._ecdsa_words(curve, items))
            return sharded_verify_batch_secp256k1(
                self.mesh, self._ecdsa_kernel_items(curve, items))
        if (self.mesh is not None and bucket == "secp256r1"
                and wc_ops.words_prep_available(curve)):
            # the half-gcd split kernel's mesh variant (no item-tuple mesh
            # fallback: without the native prep the single-chip path below
            # is the same python prep the mesh would run host-side anyway)
            from ..parallel import sharded_verify_batch_secp256r1_words
            return sharded_verify_batch_secp256r1_words(
                self.mesh, *self._ecdsa_words(curve, items))
        return wc_ops.verify_batch(curve, self._ecdsa_kernel_items(curve,
                                                                   items))

    def _start_ecdsa(self, bucket: str, items: list[_Pending]):
        from ..ops import weierstrass as wc_ops
        curve = ecmath.SECP256K1 if bucket == "secp256k1" else ecmath.SECP256R1
        if wc_ops.words_prep_available(curve):
            pending = wc_ops.verify_batch_async_words(
                curve, *self._ecdsa_words(curve, items))
        else:
            pending = wc_ops.verify_batch_async(
                curve, self._ecdsa_kernel_items(curve, items))
        return pending, wc_ops.finish_batch
