"""Cross-transaction signature batching onto device kernels.

The TPU answer to the reference's per-signature JCA calls inside
`SignedTransaction.checkSignaturesAreValid` (SignedTransaction.kt:96-100 →
Crypto.doVerify, Crypto.kt:473-496): many flows/transactions submit
(key, signature, content) checks concurrently; a dispatcher thread drains
them, buckets by scheme (mixed-scheme batches would diverge on device —
BASELINE.md config 2), and runs ONE batched kernel per scheme bucket.

Latency/throughput trade: a flush triggers at ``max_batch`` items or after
``max_latency_s`` from the first queued item — the p50 @ batch=1 metric pulls
against batch-size throughput (SURVEY.md §7 hard part 4).

Profiling: set CORDA_TPU_PROFILE_DIR to capture a JAX profiler trace of the
device dispatches (each batch is a named StepTraceAnnotation; view with
TensorBoard / xprof). The reference's analog is YourKit/JMX on the verifier
JVM (SURVEY.md §5 tracing).
"""
from __future__ import annotations

import os
import threading
import time as _time
from concurrent.futures import Future
from dataclasses import dataclass

from ..core.crypto import ecmath
from ..core.crypto.keys import PublicKey, sec1_decompress_cached
from ..core.crypto.schemes import (
    ECDSA_SECP256K1_SHA256, ECDSA_SECP256R1_SHA256, EDDSA_ED25519_SHA512)
from ..core.crypto.signatures import Crypto
from ..observability import get_tracer
from ..utils.metrics import MetricRegistry

_ED = EDDSA_ED25519_SHA512.scheme_number_id
_K1 = ECDSA_SECP256K1_SHA256.scheme_number_id
_R1 = ECDSA_SECP256R1_SHA256.scheme_number_id

_BUCKETS = {_ED: "ed25519", _K1: "secp256k1", _R1: "secp256r1"}


class _Group:
    """Shared accumulator for submit_group: ONE future resolves to the
    verdict list (per-item Future objects measured ~25µs each end-to-end —
    real money at 32k-item service batches)."""

    __slots__ = ("future", "results", "remaining", "lock")

    def __init__(self, n: int):
        self.future = Future()
        self.results = [False] * n
        self.remaining = n
        self.lock = threading.Lock()


@dataclass
class _Pending:
    key: PublicKey
    signature: bytes
    content: bytes
    future: Future | None = None
    group: "_Group | None" = None
    index: int = 0
    # tracing (observability.tracing): the submitter's SpanContext, carried
    # across the dispatcher/finisher threads; t_enq is the wall-clock
    # enqueue time for the retroactive enqueue-wait span. Both stay at
    # their defaults when tracing is off — zero cost.
    ctx: object = None
    t_enq: float = 0.0


class _null_ctx:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class SignatureBatcher:
    """Accepts individual signature checks, returns Future[bool] verdicts,
    dispatches device-batched kernels per scheme from a background thread.

    Batch-size policy (VERDICT r2 #1): the cap defaults to the kernels'
    measured throughput sweet spot (32k; BASELINE.md "the fixed ~140 ms
    dispatch floor amortizes past batch ~8k") and the drain adapts to load —
    kernels pad to power-of-two buckets so variable batch sizes compile once
    per bucket, not per length. Batches *below* ``host_crossover`` route to
    the host verify path instead: with a ~140 ms device dispatch floor and
    ~2k verifies/s on one host core, a batch under ~200 items finishes on
    host before the device kernel would even launch — this is what makes
    p50 @ batch=1 milliseconds instead of the dispatch floor. Below the
    crossover the dispatcher also skips the linger wait, so a lone submit
    is not taxed ``max_latency_s`` for a batch that was never coming."""

    def __init__(self, max_batch: int = 32768, max_latency_s: float = 0.005,
                 metrics: MetricRegistry | None = None, use_device: bool = True,
                 host_crossover: int = 192, mesh=None):
        self.max_batch = max_batch
        self.max_latency_s = max_latency_s
        self.metrics = metrics if metrics is not None else MetricRegistry()
        self.use_device = use_device
        self.host_crossover = host_crossover
        # a jax.sharding.Mesh shards every device batch over the local chips
        # (shard_map dp axis) — one node's batcher drives the whole slice
        self.mesh = mesh
        self._lock = threading.Condition()
        self._queues: dict[str, list[_Pending]] = {
            "ed25519": [], "secp256k1": [], "secp256r1": [], "host": []}
        self._closed = False
        self._finish_futures: list = []
        self._finisher = None
        self._profile_dir = os.environ.get("CORDA_TPU_PROFILE_DIR")
        self._profiling = False
        self._batch_seq = 0
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="sig-batcher")
        self._thread.start()

    # -- client side ---------------------------------------------------------
    def submit(self, key: PublicKey, signature: bytes, content: bytes,
               ctx=None) -> Future:
        """Future resolves to bool (valid/invalid); malformed input → False,
        matching the batch kernels' precheck semantics."""
        return self.submit_many([(key, signature, content)], ctx=ctx)[0]

    def submit_many(self, checks, ctx=None) -> list[Future]:
        """Bulk submission: one lock round for a whole transaction's (or
        ledger's) signature set — the per-item lock churn matters at the
        32k-batch scale the service path runs. ``ctx`` is the submitter's
        SpanContext: the flushed batch's spans join that trace."""
        pendings = [_Pending(key, sig, content, future=Future())
                    for key, sig, content in checks]
        self._stamp_trace(pendings, ctx)
        self._enqueue(pendings)
        return [p.future for p in pendings]

    def submit_group(self, checks, ctx=None) -> Future:
        """Submit a set of checks resolved by ONE future of verdict bools
        (in submission order) — the bulk interface for callers that consume
        whole batches (the OOP worker, service benchmarks)."""
        group = _Group(len(checks))
        pendings = [_Pending(key, sig, content, group=group, index=i)
                    for i, (key, sig, content) in enumerate(checks)]
        self._stamp_trace(pendings, ctx)
        self._enqueue(pendings)
        if not pendings:
            group.future.set_result([])
        return group.future

    @staticmethod
    def _stamp_trace(pendings, ctx) -> None:
        if ctx is None:     # tracing off, or an untraced caller
            return
        now = _time.time()
        for p in pendings:
            p.ctx = ctx
            p.t_enq = now

    def _enqueue(self, pendings: list[_Pending]) -> None:
        # bucket lookups happen OUTSIDE the condition lock: a 32k-item
        # submission must not hold the dispatcher up for the whole scan
        routed = [(p, "host" if not self.use_device
                   else _BUCKETS.get(p.key.scheme.scheme_number_id, "host"))
                  for p in pendings]
        with self._lock:
            if self._closed:
                raise RuntimeError("SignatureBatcher is closed")
            for p, bucket in routed:
                self._queues[bucket].append(p)
            self.metrics.counter("SigBatcher.InFlight").inc(len(pendings))
            self._lock.notify()

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._lock.notify()
        self._thread.join(timeout=5)
        if self._finisher is not None:
            self._finisher.shutdown(wait=True)
        if self._profiling:
            import jax
            jax.profiler.stop_trace()
            self._profiling = False

    # -- dispatcher ----------------------------------------------------------
    def _run(self) -> None:
        # Pipelined across TWO threads: this thread preps + launches the
        # next batch while the finisher thread blocks on earlier batches'
        # device results (a GIL-releasing wait) and resolves their futures.
        # Up to two batches stay in flight on the device (depth 2).
        self._finish_futures = []
        while True:
            with self._lock:
                while (not self._closed and not any(self._queues.values())
                       and not self._finish_futures):
                    self._lock.wait()
                if not any(self._queues.values()) and \
                        not self._finish_futures and self._closed:
                    return
                # linger only when a device-scale batch is building: below
                # the host crossover these items go to the host path anyway,
                # so waiting would add pure latency (the p50@1 case).
                # The linger is a WINDOW, not a single wait: each arriving
                # submit notifies the condition, and returning on the first
                # notification would fragment a burst of N submits into many
                # tiny batches — keep collecting until the deadline passes
                # or a full batch builds.
                depth = max((len(q) for q in self._queues.values()),
                            default=0)
                # flush reason (traced per batch): why the drain fired now
                if self._closed:
                    reason = "close"
                elif depth >= self.max_batch:
                    reason = "max_batch"
                elif depth < self.host_crossover:
                    reason = "small_batch"   # host route: no linger paid
                else:
                    reason = "deadline"
                if (self.host_crossover <= depth < self.max_batch
                        and not self._closed and any(self._queues.values())):
                    # Dispatch-on-crossover (VERDICT r4 #7): the window is
                    # bounded by max_latency_s but FLUSHES EARLY as soon as
                    # one tick passes with no queue growth — an atomic
                    # burst (one submit_group) stops paying the whole
                    # linger, while a trickling burst keeps coalescing
                    # because every enqueue notifies the condition.
                    deadline = _time.monotonic() + self.max_latency_s
                    tick = self.max_latency_s / 5
                    while not self._closed and depth < self.max_batch:
                        remaining = deadline - _time.monotonic()
                        if remaining <= 0:
                            break
                        self._lock.wait(timeout=min(remaining, tick))
                        new_depth = max((len(q)
                                         for q in self._queues.values()),
                                        default=0)
                        if new_depth == depth:
                            reason = "stalled"  # flush what we have
                            break
                        depth = new_depth
                    else:
                        reason = "close" if self._closed else "max_batch"
                drained = {name: q[: self.max_batch]
                           for name, q in self._queues.items() if q}
                for name, items in drained.items():
                    del self._queues[name][: len(items)]
            if not drained:
                self._await_finisher()
                continue
            for name, items in drained.items():
                self._flush(name, items, reason)

    def _flush(self, bucket: str, items: list[_Pending], reason: str) -> None:
        """Route one drained bucket: host loop below the crossover, device
        kernels above. Records the per-flush histogram + trace spans."""
        self.metrics.histogram("verifier_batch_size").update(len(items))
        tracer = get_tracer()
        bctx = self._trace_flush(tracer, bucket, items, reason) \
            if tracer.enabled else None
        if bucket == "host" or len(items) < self.host_crossover:
            if bucket != "host":
                self.metrics.meter("SigBatcher.HostRouted").mark(len(items))
            t0 = _time.perf_counter()
            with tracer.span("batcher.dispatch", parent=bctx, bucket=bucket,
                             batch_size=len(items), route="host"):
                verdicts = self._run_host(items)
            self.metrics.histogram("verifier_dispatch_seconds").update(
                _time.perf_counter() - t0)
            self._resolve("host", items, verdicts, bctx)
        else:
            self._dispatch_device(bucket, items, reason, bctx)

    #: Per-flush cap on retroactive enqueue-wait spans: a fully-traced 32k
    #: batch must not turn one flush into 32k ring inserts.
    MAX_WAIT_SPANS = 64

    def _trace_flush(self, tracer, bucket, items, reason):
        """Record the flush span (+ capped per-item enqueue-wait spans) and
        return its context — the parent for dispatch/wait/resolve spans.
        A mixed batch carries many traces; the flush span joins the FIRST
        traced submitter's trace and tags how many others rode along."""
        now = _time.time()
        first_ctx = None
        traced = 0
        for p in items:
            if p.ctx is None:
                continue
            traced += 1
            if first_ctx is None:
                first_ctx = p.ctx
            if traced <= self.MAX_WAIT_SPANS:
                tracer.record("batcher.enqueue_wait", parent=p.ctx,
                              start_s=p.t_enq,
                              duration_s=max(0.0, now - p.t_enq),
                              bucket=bucket)
        return tracer.record("batcher.flush", parent=first_ctx, start_s=now,
                             bucket=bucket, batch_size=len(items),
                             flush_reason=reason, n_traced=traced)

    #: Max device batches in flight: the one just launched plus two awaiting
    #: their results. A/B on v5e (3 runs each, 32k batches): 3-deep
    #: 26.6-29.4k/s; strict 2-deep (gate before launch) 21.0-22.7k/s;
    #: 1-deep 18.8-22.8k/s. Worst-case extra device residency is one
    #: batch's buffers (~tens of MB at 32k) — noise against HBM.
    MAX_IN_FLIGHT = 3

    def _dispatch_device(self, bucket: str, items: list[_Pending],
                         reason: str = "full", bctx=None) -> None:
        profile_ctx = None
        if self._profile_dir is not None:
            import jax
            if not self._profiling:
                jax.profiler.start_trace(self._profile_dir)
                self._profiling = True
            self._batch_seq += 1
            profile_ctx = jax.profiler.StepTraceAnnotation(
                f"verify-{bucket}", step_num=self._batch_seq)
        tracer = get_tracer()
        dspan = tracer.span("batcher.dispatch", parent=bctx, bucket=bucket,
                            batch_size=len(items), route="device",
                            flush_reason=reason)
        t_prep = _time.perf_counter()
        try:
            with self.metrics.timer(f"SigBatcher.{bucket}.Prep"), \
                    (profile_ctx or _null_ctx()):
                if self.mesh is not None:
                    # mesh path resolves immediately (sharded helpers force)
                    if bucket == "ed25519":
                        verdicts = self._run_ed25519(items)
                    else:
                        verdicts = self._run_ecdsa(bucket, items)
                    self._mark_device(items)
                    self.metrics.histogram("verifier_dispatch_seconds"
                                           ).update(_time.perf_counter()
                                                    - t_prep)
                    dspan.set_tag("mesh", True)
                    dspan.finish()
                    self._resolve(bucket, items, verdicts, bctx)
                    return
                # host prep HERE — overlaps the finisher's device wait
                if bucket == "ed25519":
                    pending, finish = self._start_ed25519(items)
                else:
                    pending, finish = self._start_ecdsa(bucket, items)
        except Exception:
            # batch-level failure (kernel/compile/transfer): fall back to
            # per-item host verification so one malformed member — or a
            # transient device error — cannot fail unrelated transactions'
            # futures (VERDICT r2 weak #9)
            self.metrics.meter("SigBatcher.BatchFailure").mark()
            dspan.set_tag("fallback", "host")
            dspan.finish()
            self._resolve(bucket, items, self._run_host(items), bctx)
            return
        self.metrics.histogram("verifier_prep_seconds").update(
            _time.perf_counter() - t_prep)
        dspan.finish()
        # pipelined: launch first, then drain down to MAX_IN_FLIGHT-1
        # awaited batches — overlapping transfers with compute on device
        if self._finisher is None:
            from concurrent.futures import ThreadPoolExecutor
            self._finisher = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="sig-batcher-finish")
        self._finish_futures.append(self._finisher.submit(
            self._finish_one, bucket, items, pending, finish, bctx))
        while len(self._finish_futures) >= self.MAX_IN_FLIGHT:
            self._pop_finisher()

    def _pop_finisher(self) -> None:
        """Wait out the OLDEST in-flight batch. A finisher crash must not
        kill the dispatcher thread — every queued caller would hang."""
        if not self._finish_futures:
            return
        try:
            self._finish_futures.pop(0).result()
        except Exception:
            import logging
            logging.getLogger(__name__).exception(
                "signature batch finisher failed")
            self.metrics.meter("SigBatcher.BatchFailure").mark()

    def _await_finisher(self) -> None:
        # drain ONE batch, then let the caller loop re-check the queues: a
        # latency-sensitive submit arriving mid-drain must not wait for the
        # whole in-flight window (review r3)
        self._pop_finisher()

    def _finish_one(self, bucket, items, pending, finish, bctx=None) -> None:
        # bctx crossed from the dispatcher thread via the executor args —
        # the explicit-propagation seam the tracer tests pin down
        wspan = get_tracer().span("batcher.device_wait", parent=bctx,
                                  bucket=bucket, batch_size=len(items))
        t0 = _time.perf_counter()
        try:
            with wspan, self.metrics.timer(f"SigBatcher.{bucket}.Duration"):
                verdicts = finish(pending)
            self._mark_device(items)
            self.metrics.histogram("verifier_dispatch_seconds").update(
                _time.perf_counter() - t0)
        except Exception:
            self.metrics.meter("SigBatcher.BatchFailure").mark()
            verdicts = self._run_host(items)
        self._resolve(bucket, items, verdicts, bctx)

    def _mark_device(self, items) -> None:
        self.metrics.meter("SigBatcher.DeviceBatches").mark()
        self.metrics.meter("SigBatcher.DeviceChecked").mark(len(items))

    def _resolve(self, bucket: str, items: list[_Pending], verdicts,
                 bctx=None) -> None:
        tracer = get_tracer()
        t_wall = _time.time() if tracer.enabled else 0.0
        t0 = _time.perf_counter()
        done_groups = []
        for p, ok in zip(items, verdicts):
            if p.group is not None:
                g = p.group
                with g.lock:
                    g.results[p.index] = bool(ok)
                    g.remaining -= 1
                    if g.remaining == 0:
                        done_groups.append(g)
            else:
                try:
                    p.future.set_result(bool(ok))
                except Exception:
                    pass   # caller cancelled its future; verdict dropped
        for g in done_groups:
            try:
                g.future.set_result(g.results)
            except Exception:
                pass
        self.metrics.meter("SigBatcher.Checked").mark(len(items))
        self.metrics.counter("SigBatcher.InFlight").dec(len(items))
        dt = _time.perf_counter() - t0
        self.metrics.histogram("verifier_finish_seconds").update(dt)
        if tracer.enabled:
            tracer.record("batcher.resolve", parent=bctx, start_s=t_wall,
                          duration_s=dt, bucket=bucket,
                          batch_size=len(items))

    @staticmethod
    def _run_host(items: list[_Pending]) -> list[bool]:
        verdicts = []
        for p in items:
            try:
                verdicts.append(Crypto.is_valid(p.key, p.signature, p.content))
            except Exception:
                verdicts.append(False)
        return verdicts

    def _run_ed25519(self, items: list[_Pending]):
        triples = [(p.key.encoded, p.signature, p.content) for p in items]
        if self.mesh is not None:
            from ..parallel import sharded_verify_batch_ed25519
            return sharded_verify_batch_ed25519(self.mesh, triples)
        from ..ops import ed25519 as ed_ops
        return ed_ops.verify_batch(triples)

    @staticmethod
    def _start_ed25519(items: list[_Pending]):
        from ..ops import ed25519 as ed_ops
        pending = ed_ops.verify_batch_async(
            [(p.key.encoded, p.signature, p.content) for p in items])
        return pending, ed_ops.finish_batch

    @staticmethod
    def _ecdsa_kernel_items(curve, items: list[_Pending]):
        kitems = []
        for p in items:
            # per-item isolation: ANY malformed member becomes a False
            # verdict for that member alone, never a batch failure
            try:
                point = sec1_decompress_cached(curve, p.key.encoded)
                r, s = ecmath.ecdsa_sig_from_der(p.signature)
            except Exception:
                point, r, s = None, 0, 0  # fails the range precheck → False
            kitems.append((point, p.content, r, s))
        return kitems

    def _run_ecdsa(self, bucket: str, items: list[_Pending]):
        from ..ops import weierstrass as wc_ops
        curve = ecmath.SECP256K1 if bucket == "secp256k1" else ecmath.SECP256R1
        kitems = self._ecdsa_kernel_items(curve, items)
        if self.mesh is not None and bucket == "secp256k1":
            from ..parallel import sharded_verify_batch_secp256k1
            return sharded_verify_batch_secp256k1(self.mesh, kitems)
        return wc_ops.verify_batch(curve, kitems)

    def _start_ecdsa(self, bucket: str, items: list[_Pending]):
        from ..ops import weierstrass as wc_ops
        curve = ecmath.SECP256K1 if bucket == "secp256k1" else ecmath.SECP256R1
        pending = wc_ops.verify_batch_async(
            curve, self._ecdsa_kernel_items(curve, items))
        return pending, wc_ops.finish_batch
