"""SLO-fed fleet controller: autoscaling + a stepped degradation ladder.

Closes the robustness loop (ROADMAP item 3): every signal this module
consumes already exists — SLOTracker burn-rate alerts (observability/
slo.py), the router's capacity-normalized queue-depth estimates and
per-worker service-rate EWMAs (verifier/out_of_process.py), breaker
states (verifier/batcher.py) and load-report staleness (fleet_status) —
but until now nothing ACTED on them. The FleetController is a small
deterministic control loop (injectable clock, pure callables for every
actuator) that:

- **scales** the fleet through the existing join/leave seams: a spawn
  callable rides WorkerHello, retirement rides the graceful Goodbye, and
  dead workers are crash-detached via the stale-reaper so their charged
  work requeues (zero lost futures — the same exactly-once machinery the
  chaos suite pins);
- **degrades in steps, not off a cliff**: a *ladder* of reversible
  rungs applied one per tick under sustained stress and reverted in
  reverse order on recovery — shed bulk admission-class load first
  (backpressure lands on throughput traffic), then shrink the bulk
  batch ladder (smaller, lower-latency device batches), then route
  interactive traffic to host verify (bounded latency even with the
  device path saturated);
- **hysteresis**: scale/step cooldowns plus a consecutive-healthy-tick
  requirement before any reversal, so an oscillating signal cannot flap
  the fleet.

Every action is triple-logged: a ``controller.*`` jlog event, a
``Controller.*`` meter mark, and a span under the live
``controller.episode`` span — one annotated timeline per
stress-to-recovery episode on /traces. ``status()`` is the
``controller`` block on ``/readyz``, ``fleet_status()`` and
``fleetstat``.
"""
from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable

from ..observability import get_tracer
from ..observability.slog import jlog
from ..utils.metrics import MetricRegistry

log = logging.getLogger(__name__)

#: Controller states (gauge codes in Controller.State).
STEADY = "steady"          # no stress, no ladder rung applied, no episode
STRESSED = "stressed"      # SLO burning / queue trending up; scaling out
DEGRADED = "degraded"      # at least one ladder rung is applied
RECOVERING = "recovering"  # stress gone, episode not yet closed

_STATE_CODES = {STEADY: 0, STRESSED: 1, DEGRADED: 2, RECOVERING: 3}


@dataclass
class LadderStep:
    """One reversible degradation rung: ``apply`` sheds load, ``revert``
    restores it. Steps are applied front-to-back and reverted back-to-
    front, so the cheapest concession is always the first taken and the
    last returned."""

    name: str
    apply: Callable[[], None]
    revert: Callable[[], None]
    applied: bool = False


@dataclass
class ControllerConfig:
    """Thresholds + hysteresis. Defaults suit the in-process fleet's
    millisecond report cadence; production TCP fleets scale them up with
    their report interval."""

    min_workers: int = 1
    max_workers: int = 8
    #: Seconds between scale actions (up or down) — one worker per
    #: cooldown keeps a burst from over-spawning before new capacity
    #: even reports in.
    scale_cooldown_s: float = 1.0
    #: Seconds between ladder transitions (either direction).
    step_cooldown_s: float = 0.5
    #: Consecutive healthy ticks required before ANY reversal (ladder
    #: revert or scale-down) — the anti-flap guard.
    healthy_ticks: int = 3
    #: Per-worker estimated queue depth (signatures) above which the
    #: fleet counts as stressed even without an SLO alert yet.
    queue_high: float = 256.0
    #: Per-worker depth below which the fleet counts as drained.
    queue_low: float = 32.0
    #: Scale-down additionally requires this much error budget left on
    #: every objective — never give capacity back while the budget is
    #: still scorched.
    budget_scale_down_pct: float = 50.0
    #: EWMA smoothing for the queue-depth trend signal.
    trend_alpha: float = 0.3
    #: Open device breakers count toward stress (a scheme falling back
    #: to host verify is a capacity loss the queue numbers lag on).
    breakers_stress: bool = True


class FleetController:
    """The control loop. Everything it observes and actuates is injected
    as a callable, so unit tests drive it with a fake clock and stub
    seams while production wires the real fleet (InProcessFleet.
    attach_controller / a node's fleet runner).

    ``tick()`` is one evaluation: gather signals, take at most one
    scale/ladder action (stale reaping is exempt — dead workers hold
    charged work hostage, so every tick sweeps them), update state.
    Thread-safe; callers run it from any timer loop.
    """

    def __init__(self, *, slo, worker_count: Callable[[], int],
                 queue_depth: Callable[[], float],
                 spawn: Callable[[], object] | None = None,
                 retire: Callable[[], object] | None = None,
                 reap_stale: Callable[[], list] | None = None,
                 breaker_open_count: Callable[[], int] | None = None,
                 ladder: tuple = (),
                 config: ControllerConfig | None = None,
                 clock: Callable[[], float] = time.monotonic,
                 metrics: MetricRegistry | None = None):
        self.slo = slo
        self.config = config if config is not None else ControllerConfig()
        self.metrics = metrics if metrics is not None else MetricRegistry()
        self.clock = clock
        self._worker_count = worker_count
        self._queue_depth = queue_depth
        self._spawn = spawn
        self._retire = retire
        self._reap_stale = reap_stale
        self._breaker_open_count = breaker_open_count
        self.ladder: list[LadderStep] = list(ladder)
        self._lock = threading.RLock()
        self._state = STEADY
        self._healthy_streak = 0
        self._depth_trend: float | None = None
        self._last_scale = -float("inf")
        self._last_step = -float("inf")
        #: workers added by THIS controller and not yet given back — the
        #: controller only ever retires capacity it spawned, so a healthy
        #: fleet at its operator-provisioned size sees zero actions
        self._net_spawned = 0
        self._actions_total = 0
        self._recent: deque = deque(maxlen=64)
        self._episodes = 0
        self._episode_started: float | None = None
        self._episode_span = None
        self._recovery_s_last: float | None = None
        m = self.metrics
        m.gauge("Controller.State",
                lambda: _STATE_CODES.get(self._state, 0))
        m.gauge("Controller.LadderStep", lambda: self.ladder_step)
        m.gauge("Controller.Workers", lambda: int(self._worker_count()))
        m.meter("Controller.Actions")

    # -- signal views --------------------------------------------------------
    @property
    def state(self) -> str:
        return self._state

    @property
    def actions_total(self) -> int:
        return self._actions_total

    @property
    def ladder_step(self) -> int:
        return sum(1 for s in self.ladder if s.applied)

    def _alerts(self) -> list:
        if self.slo is None:
            return []
        try:
            return list(self.slo.alerts())
        except Exception:
            log.exception("SLO alert read failed")
            return []

    def _budget_ok(self) -> bool:
        """Every objective's remaining error budget clears the scale-down
        bar (vacuously true without an SLO tracker)."""
        if self.slo is None:
            return True
        try:
            return all(
                self.slo.error_budget_pct(obj)
                >= self.config.budget_scale_down_pct
                for obj in self.slo.objectives)
        except Exception:
            log.exception("SLO budget read failed")
            return False

    # -- the loop ------------------------------------------------------------
    def tick(self, now: float | None = None) -> list[dict]:
        """One control-loop evaluation; returns the actions it took (also
        recorded on the jlog/metrics/span planes)."""
        with self._lock:
            now = self.clock() if now is None else now
            cfg = self.config
            actions: list[dict] = []
            # dead workers first, in ANY state: their charged work is
            # unreachable until the crash-detach requeues it
            if self._reap_stale is not None:
                try:
                    reaped = list(self._reap_stale() or ())
                except Exception:
                    log.exception("stale reap failed")
                    reaped = []
                for w in reaped:
                    actions.append(self._act("stale_detach", now, worker=w))
            workers = max(1, int(self._worker_count()))
            depth = float(self._queue_depth())
            per_worker = depth / workers
            if self._depth_trend is None:
                self._depth_trend = per_worker
            else:
                self._depth_trend = (cfg.trend_alpha * per_worker
                                     + (1.0 - cfg.trend_alpha)
                                     * self._depth_trend)
            alerts = self._alerts()
            breakers_open = 0
            if cfg.breakers_stress and self._breaker_open_count is not None:
                try:
                    breakers_open = int(self._breaker_open_count())
                except Exception:
                    breakers_open = 0
            stressed = (bool(alerts)
                        or self._depth_trend > cfg.queue_high
                        or breakers_open > 0)
            healthy = (not alerts and breakers_open == 0
                       and self._depth_trend < cfg.queue_low)
            if stressed:
                self._healthy_streak = 0
                actions.extend(self._escalate_locked(now, workers, alerts,
                                                     per_worker))
            elif healthy:
                self._healthy_streak += 1
                if self._healthy_streak >= cfg.healthy_ticks:
                    actions.extend(self._relax_locked(now, workers))
            else:
                # in the hysteresis band: hold position, reset the streak
                # so a reversal needs SUSTAINED health, not a blip
                self._healthy_streak = 0
            self._update_state_locked(now, stressed, healthy)
            return actions

    def _escalate_locked(self, now: float, workers: int, alerts: list,
                         per_worker: float) -> list[dict]:
        cfg = self.config
        severity = alerts[0]["severity"] if alerts else "none"
        if (self._spawn is not None and workers < cfg.max_workers
                and now - self._last_scale >= cfg.scale_cooldown_s):
            self._last_scale = now
            try:
                spawned = self._spawn()
            except Exception:
                log.exception("controller spawn failed")
                return []
            self._net_spawned += 1
            return [self._act("scale_up", now, workers=workers + 1,
                              worker=str(spawned) if spawned else None,
                              severity=severity,
                              queue_per_worker=round(per_worker, 1))]
        step = next((s for s in self.ladder if not s.applied), None)
        if step is not None and now - self._last_step >= cfg.step_cooldown_s:
            self._last_step = now
            try:
                step.apply()
            except Exception:
                log.exception("ladder step %s apply failed", step.name)
                return []
            step.applied = True
            return [self._act("degrade", now, step=step.name,
                              rung=self.ladder_step, severity=severity,
                              queue_per_worker=round(per_worker, 1))]
        return []

    def _relax_locked(self, now: float, workers: int) -> list[dict]:
        cfg = self.config
        applied = [s for s in self.ladder if s.applied]
        if applied:
            if now - self._last_step < cfg.step_cooldown_s:
                return []
            step = applied[-1]
            self._last_step = now
            try:
                step.revert()
            except Exception:
                log.exception("ladder step %s revert failed", step.name)
                return []
            step.applied = False
            return [self._act("restore", now, step=step.name,
                              rung=self.ladder_step, opens_episode=False)]
        if (self._retire is not None and workers > cfg.min_workers
                and self._net_spawned > 0
                and self._budget_ok()
                and now - self._last_scale >= cfg.scale_cooldown_s):
            self._last_scale = now
            try:
                retired = self._retire()
            except Exception:
                log.exception("controller retire failed")
                return []
            self._net_spawned -= 1
            return [self._act("scale_down", now, workers=workers - 1,
                              worker=str(retired) if retired else None,
                              opens_episode=False)]
        return []

    def _update_state_locked(self, now: float, stressed: bool,
                             healthy: bool) -> None:
        if self.ladder_step > 0:
            new = DEGRADED
        elif stressed:
            new = STRESSED
        elif self._episode_started is not None:
            # past stress cooling off — stay out of "steady" until the
            # healthy streak clears the hysteresis bar
            new = (STEADY if self._healthy_streak >= self.config.healthy_ticks
                   else RECOVERING)
        else:
            new = STEADY
        if new == STEADY and self._episode_started is not None:
            self._recovery_s_last = now - self._episode_started
            jlog(log, "controller.recovered", level=logging.INFO,
                 recovery_s=round(self._recovery_s_last, 3),
                 actions=self._actions_total)
            if self._episode_span is not None:
                self._episode_span.set_tag(
                    "recovery_s", round(self._recovery_s_last, 3))
                self._episode_span.set_tag("actions", self._actions_total)
                self._episode_span.finish()
                self._episode_span = None
            self._episode_started = None
        self._state = new

    def _act(self, kind: str, now: float, opens_episode: bool = True,
             **fields) -> dict:
        """Record one action on every observability plane and open the
        episode if this is the first action since steady state. Relax
        actions (ladder restore, scale-down) pass ``opens_episode=False``
        — giving healthy capacity back is housekeeping, not an incident,
        so it must not start a new /traces timeline of its own."""
        fields = {k: v for k, v in fields.items() if v is not None}
        if self._episode_started is None and opens_episode:
            self._episode_started = now
            self._episodes += 1
            # an incident re-arms the hysteresis: health must be re-proven
            # from scratch before this episode may close
            self._healthy_streak = 0
            tracer = get_tracer()
            if tracer.enabled:
                self._episode_span = tracer.span("controller.episode",
                                                 episode=self._episodes)
        self._actions_total += 1
        self.metrics.meter("Controller.Actions").mark()
        self.metrics.meter(f"Controller.Actions.{kind}").mark()
        record = {"action": kind, "t": round(now, 3), **fields}
        self._recent.append(record)
        jlog(log, f"controller.{kind}", level=logging.INFO, **fields)
        if self._episode_span is not None:
            parent = self._episode_span.context()
            get_tracer().record(f"controller.{kind}", parent=parent,
                                **fields)
        return record

    # -- the /readyz + fleetstat block ---------------------------------------
    def status(self) -> dict:
        with self._lock:
            return {
                "state": self._state,
                "workers": int(self._worker_count()),
                "queue_depth_trend": (round(self._depth_trend, 1)
                                      if self._depth_trend is not None
                                      else None),
                "ladder": [{"name": s.name, "applied": s.applied}
                           for s in self.ladder],
                "ladder_step": self.ladder_step,
                "actions_total": self._actions_total,
                "recent_actions": list(self._recent)[-8:],
                "episodes": self._episodes,
                "recovery_s_last": (round(self._recovery_s_last, 3)
                                    if self._recovery_s_last is not None
                                    else None),
                "healthy_streak": self._healthy_streak,
            }


def batcher_ladder(batchers) -> tuple:
    """The standard three-rung degradation ladder over a set of
    SignatureBatchers (one per fleet worker, or a node's single batcher):
    shed bulk admission first, shrink the bulk batch ladder second, route
    interactive to host verify last. Each rung fans out to every batcher
    in the (live) sequence — pass a mutable list and newly spawned
    workers' batchers inherit the currently applied rungs via
    ``apply_degradations``."""

    def _fan(method: str, on: bool) -> None:
        for b in list(batchers):
            try:
                getattr(b, method)(on)
            except Exception:
                log.exception("%s(%s) failed on %r", method, on, b)

    return (
        LadderStep("shed_bulk",
                   apply=lambda: _fan("shed_bulk", True),
                   revert=lambda: _fan("shed_bulk", False)),
        LadderStep("shrink_ladder",
                   apply=lambda: _fan("shrink_ladder", True),
                   revert=lambda: _fan("shrink_ladder", False)),
        LadderStep("host_route_interactive",
                   apply=lambda: _fan("route_interactive_host", True),
                   revert=lambda: _fan("route_interactive_host", False)),
    )


def apply_degradations(ladder, batcher) -> None:
    """Bring a newly spawned worker's batcher up to the fleet's currently
    applied degradation rungs (a worker joining mid-episode must not
    undercut the shed)."""
    for step in ladder:
        if not step.applied:
            continue
        method = {"shed_bulk": "shed_bulk",
                  "shrink_ladder": "shrink_ladder",
                  "host_route_interactive": "route_interactive_host"
                  }.get(step.name)
        if method is None:
            continue
        try:
            getattr(batcher, method)(True)
        except Exception:
            log.exception("applying %s to spawned batcher failed", step.name)
