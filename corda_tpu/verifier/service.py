"""Async transaction-verification services behind one pluggable seam.

Reference parity:
- `TransactionVerifierService.verify(ltx) → ListenableFuture` (Services.kt:544-550)
- `InMemoryTransactionVerifierService` — fixed 4-worker pool running
  `transaction.verify()` (InMemoryTransactionVerifierService.kt:10-18)
- `OutOfProcessTransactionVerifierService` metrics names
  (OutOfProcessTransactionVerifierService.kt:33-45)

TPU-first redesign: `TpuTransactionVerifierService` splits a transaction's
verification into (a) per-signature EC checks → `SignatureBatcher` device
kernels, batched ACROSS transactions; (b) signature-coverage / platform-rule /
contract-code checks → host thread pool. The `VerifierType`-style selection
seam (NodeConfiguration.kt:91-94) is `make_verifier_service`.
"""
from __future__ import annotations

import time
from concurrent.futures import Future, ThreadPoolExecutor

from ..core.crypto.signatures import SignatureException
from ..observability import get_tracer
from ..utils.metrics import MetricRegistry
from .batcher import SignatureBatcher


class TransactionVerifierService:
    """SPI: async verification of a resolved LedgerTransaction. Subclasses
    share the metrics-instrumented submission path (the named metrics of
    OutOfProcessTransactionVerifierService.kt:33-45)."""

    metrics: MetricRegistry
    _pool: ThreadPoolExecutor

    #: capability flag callers probe before passing trace_ctx — a custom
    #: service with the pre-observability signature keeps working
    supports_trace_ctx = True

    def verify(self, ltx, trace_ctx=None) -> Future:
        return self._submit_instrumented(ltx.verify, trace_ctx=trace_ctx)

    def verify_signed(self, stx, services,
                      check_sufficient_signatures: bool = True,
                      trace_ctx=None) -> Future:
        """Async full verify of a SignedTransaction on the service's pool —
        the future every backend offers the SMM's Verify suspension point
        (flows park on it instead of blocking the node thread). Subclasses
        accelerate it (Tpu: device-batched signatures; OutOfProcess: worker
        fan-out); this base version runs `stx.verify` host-side."""
        return self._submit_instrumented(
            lambda: stx.verify(
                services,
                check_sufficient_signatures=check_sufficient_signatures),
            trace_ctx=trace_ctx)

    def _submit_instrumented(self, work_fn, trace_ctx=None) -> Future:
        self.metrics.counter("Verification.InFlight").inc()
        hist = self.metrics.histogram("tx_verify_seconds")
        tracer = get_tracer()

        def work():
            t0 = time.perf_counter()
            with self.metrics.timer("Verification.Duration"), \
                    tracer.span("verifier.run", parent=trace_ctx):
                try:
                    result = work_fn()
                    self.metrics.meter("Verification.Success").mark()
                    return result
                except Exception:
                    self.metrics.meter("Verification.Failure").mark()
                    raise
                finally:
                    self.metrics.counter("Verification.InFlight").dec()
                    hist.update(time.perf_counter() - t0,
                                trace_id=getattr(trace_ctx, "trace_id", None))

        return self._pool.submit(work)

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False)


class InMemoryTransactionVerifierService(TransactionVerifierService):
    """Host thread-pool backend (InMemoryTransactionVerifierService.kt:10-18)."""

    def __init__(self, workers: int = 4, metrics: MetricRegistry | None = None):
        self.metrics = metrics if metrics is not None else MetricRegistry()
        self._pool = ThreadPoolExecutor(max_workers=workers,
                                        thread_name_prefix="verifier")


class TpuTransactionVerifierService(TransactionVerifierService):
    """Device-batched backend: signatures on TPU, contract rules on host.

    `verify(ltx)` keeps the reference SPI (contract/platform rules only — the
    reference's callers have already checked signatures by the time an ltx
    exists). `verify_signed(stx, services)` is the full TPU-accelerated path:
    device-batched `check_signatures_are_valid` + coverage + resolution +
    `ltx.verify()`, semantics of SignedTransaction.verify
    (SignedTransaction.kt:174-178).
    """

    #: safe to block a flow on: the batcher + pool resolve on their own
    #: threads, never via the node's serial executor (hub.verify_transaction)
    resolves_off_node_thread = True

    def __init__(self, workers: int = 4, batcher: SignatureBatcher | None = None,
                 metrics: MetricRegistry | None = None, mesh=None):
        self.metrics = metrics if metrics is not None else MetricRegistry()
        # mesh: shard every device batch over the local chips (the node's
        # whole slice verifies as one SPMD program; corda_tpu.parallel)
        self.batcher = batcher if batcher is not None else SignatureBatcher(
            metrics=self.metrics, mesh=mesh)
        self._pool = ThreadPoolExecutor(max_workers=workers,
                                        thread_name_prefix="tpu-verifier")

    # -- full TPU path (verify(ltx) is inherited) ----------------------------
    def verify_signed(self, stx, services,
                      check_sufficient_signatures: bool = True,
                      trace_ctx=None) -> Future:
        """Async full verify of a SignedTransaction; the per-signature EC math
        rides the shared device batcher (cross-transaction batching). With
        tracing enabled the whole pipeline — submit, batch flush, device
        dispatch, resolve — lands in one trace rooted here (or in the
        caller's, when ``trace_ctx`` carries the flow's context)."""
        tracer = get_tracer()
        root = tracer.span("tx.verify", parent=trace_ctx,
                           tx_id=stx.id.bytes.hex()[:16],
                           n_sigs=len(stx.sigs))
        ctx = root.context()
        tracer.record("verifier.submit", parent=ctx, n_sigs=len(stx.sigs))
        try:
            # ONE group future for the whole signature set: per-signature
            # Future allocation measured ~25µs each — real money on
            # many-signature transactions (the batcher resolves the group
            # with one lock acquire per flush). Interactive class: a single
            # tx's few signatures are latency-bound — they flush on the
            # short deadline instead of lingering behind a bulk megabatch.
            group_future = self.batcher.submit_group(
                [(sig.by, sig.bytes, stx.id.bytes) for sig in stx.sigs],
                ctx=ctx, latency_class="interactive")

            def work():
                try:
                    for sig, ok in zip(stx.sigs, group_future.result()):
                        if not ok:
                            raise SignatureException(
                                f"Signature by {sig.by.to_string_short()} "
                                f"did not verify on transaction "
                                f"{stx.id.prefix_chars()}")
                    if check_sufficient_signatures:
                        missing = stx.get_missing_signatures()
                        if missing:
                            from ..core.transactions.signed import (
                                SignaturesMissingException)
                            raise SignaturesMissingException(
                                missing,
                                [k.to_string_short() for k in missing],
                                stx.id)
                    with tracer.span("verifier.resolve", parent=ctx):
                        stx.to_ledger_transaction(services).verify()
                finally:
                    root.finish()

            return self._submit_instrumented(work, trace_ctx=ctx)
        except Exception as exc:
            # submission failed (e.g. closed batcher / shut-down pool): the
            # root span must still close and the caller must get a FAILED
            # FUTURE, not an exception — verify_signed's contract is async
            root.finish()
            failed: Future = Future()
            failed.set_exception(exc)
            return failed

    def shutdown(self) -> None:
        super().shutdown()
        self.batcher.close()


def make_verifier_service(verifier_type: str = "InMemory", **kwargs
                          ) -> TransactionVerifierService:
    """The VerifierType config seam (NodeConfiguration.kt:91-94):
    "InMemory" | "Tpu" | "OutOfProcess".

    NOTE on the Tpu backend: only ``verify_signed(stx, ...)`` pays off on
    device — the reference-shaped ``verify(ltx)`` SPI verifies contract and
    platform rules only (an ltx's signatures are already checked by the time
    it exists), so callers holding a SignedTransaction should use
    ``verify_signed``. The node's flow path does (the SMM's Verify
    suspension point routes through verify_signed; locked by
    tests/test_verify_suspension.py's device-batch assertion).

    "OutOfProcess" needs ``network_service=`` (the node's messaging — the
    queue the worker fleet attaches to); ``expected_workers=`` sizes the
    fleet for /readyz degradation reporting."""
    if verifier_type == "InMemory":
        return InMemoryTransactionVerifierService(**kwargs)
    if verifier_type == "Tpu":
        return TpuTransactionVerifierService(**kwargs)
    if verifier_type == "OutOfProcess":
        from .out_of_process import OutOfProcessTransactionVerifierService
        return OutOfProcessTransactionVerifierService(**kwargs)
    raise ValueError(f"Unknown verifier type: {verifier_type}")
