"""In-process verifier fleet: N device-sharded workers behind one queue.

The MULTICHIP / ``bench.py --fleet`` harness. Everything rides the REAL
out-of-process protocol — ``OutOfProcessTransactionVerifierService``'s
load-aware router, ``VerifierWorker``'s stealable backlog, WorkerLoadReport
/ StealRequest / WorkReturned — but over the deterministic in-memory bus
with a background pump thread, so one process can measure fleet scaling
without spawning N OS processes (the TCP plane and
``python -m corda_tpu.verifier --num-shards`` are the production spelling
of the same topology).

Scaling efficiency is BUSY-TIME based, not count based::

    efficiency = 100 × mean_i(last_completion_i − t0) / makespan

i.e. how long each worker stayed busy relative to the whole run. A
count-based definition (total / (n × max_per_worker)) would punish
successful work stealing — stolen groups inflate the fast worker's count —
while busy-time rewards exactly what the fleet is for: nobody idles while
a straggler holds undone work.
"""
from __future__ import annotations

import time
import threading

from ..core.crypto import generate_keypair
from ..core.crypto.schemes import EDDSA_ED25519_SHA512
from ..core.crypto.signatures import Crypto
from ..network.inmemory import InMemoryMessagingNetwork
from ..observability import Tracer, get_tracer, set_tracer
from ..utils.metrics import MetricRegistry
from .batcher import SignatureBatcher
from .out_of_process import (OutOfProcessTransactionVerifierService,
                             VerifierWorker)


def make_sig_checks(n: int, unique: int = 16, seed: int = 7):
    """Deterministic honestly-signed ed25519 ``(key, sig, content)`` checks,
    ``unique`` distinct tiled to ``n`` (the bench corpus shape — signing is
    pure Python, so uniqueness is bounded like bench.py's UNIQUE)."""
    base = []
    for i in range(min(n, unique)):
        entropy = (seed * 1000003 + i).to_bytes(32, "little")
        kp = generate_keypair(EDDSA_ED25519_SHA512, entropy=entropy)
        content = (seed * 999331 + i).to_bytes(64, "little")
        sig = Crypto.do_sign(kp.private, content, kp.public)
        base.append((kp.public, sig, content))
    return (base * (n // len(base) + 1))[:n]


class InProcessFleet:
    """N ``VerifierWorker``s (each with a private ``SignatureBatcher``,
    optionally pinned to one jax device) attached to one node-side service,
    all on an in-memory bus pumped by a background thread.

    ``report_every_s`` drives ``send_load_report`` from the pump thread —
    the load/steal machinery stays live without per-worker timer threads,
    and the pump delivers the reports in the same loop."""

    def __init__(self, n_workers: int, use_device: bool = False,
                 devices=None, host_crossover: int | None = None,
                 max_latency_s: float = 0.005,
                 max_inflight_groups: int | None = 2,
                 report_every_s: float = 0.01,
                 metrics: MetricRegistry | None = None):
        if n_workers < 1:
            raise ValueError("a fleet needs at least one worker")
        if devices is not None and len(devices) < n_workers:
            raise ValueError(f"{n_workers} workers but only "
                             f"{len(devices)} devices")
        self.n_workers = n_workers
        self.metrics = metrics if metrics is not None else MetricRegistry()
        self.bus = InMemoryMessagingNetwork()
        self.service = OutOfProcessTransactionVerifierService(
            self.bus.create_node("node"), metrics=self.metrics,
            expected_workers=n_workers,
            load_report_interval_s=report_every_s)
        batcher_kwargs: dict = {"use_device": use_device,
                                "max_latency_s": max_latency_s}
        if host_crossover is not None:
            batcher_kwargs["host_crossover"] = host_crossover
        self.batchers: list[SignatureBatcher] = []
        self.workers: list[VerifierWorker] = []
        for i in range(n_workers):
            kwargs = dict(batcher_kwargs)
            shard: tuple = ()
            if devices is not None:
                kwargs["device"] = devices[i]
                shard = (getattr(devices[i], "id", i),)
            batcher = SignatureBatcher(**kwargs)
            worker = VerifierWorker(
                self.bus.create_node(f"w{i}"), "node",
                batcher=batcher, use_device=use_device,
                device_shard=shard, capacity=1,
                load_report_interval_s=None,   # pump thread reports instead
                max_inflight_groups=max_inflight_groups)
            worker._report_enabled = True      # idle pings feed the stealer
            self.batchers.append(batcher)
            self.workers.append(worker)
        self._report_every_s = report_every_s
        self._stop = threading.Event()
        self._pump = threading.Thread(target=self._pump_loop, daemon=True,
                                      name="fleet-pump")
        self._pump.start()

    def _pump_loop(self) -> None:
        last_report = 0.0
        while not self._stop.is_set():
            progressed = self.bus.run_network()
            now = time.monotonic()
            if now - last_report >= self._report_every_s:
                last_report = now
                for w in self.workers:
                    try:
                        w.send_load_report()
                    except Exception:
                        pass   # a stopped worker mid-close; pump survives
            if not progressed:
                time.sleep(0.0005)

    def verify_signatures(self, checks):
        return self.service.verify_signatures(checks)

    def steal_count(self) -> int:
        return self.metrics.meter("Fleet.Steals").count

    def stolen_count(self) -> int:
        return self.metrics.meter("Fleet.Stolen").count

    def close(self) -> None:
        self._stop.set()
        self._pump.join(timeout=5.0)
        for w in self.workers:
            try:
                w.stop(announce=False)
            except Exception:
                pass
        for b in self.batchers:
            b.close()
        self.service.shutdown()


def stitched_trace_depth(spans) -> int:
    """Deepest parent chain among traces that contain BOTH a node-side
    ``verifier.oop_submit`` span and at least one ``worker.*`` span — i.e.
    traces that actually crossed the process seam. 0 means no stitched
    trace existed (the cross-process plane was dark)."""
    by_trace: dict[str, list[dict]] = {}
    for s in spans:
        if isinstance(s, dict) and s.get("trace_id"):
            by_trace.setdefault(s["trace_id"], []).append(s)
    best = 0
    for group in by_trace.values():
        names = [s.get("name") or "" for s in group]
        if ("verifier.oop_submit" not in names
                or not any(n.startswith("worker.") for n in names)):
            continue
        by_id = {s["span_id"]: s for s in group if s.get("span_id")}
        for s in group:
            depth, cur, hops = 1, s, 0
            while cur.get("parent_id") in by_id and hops < len(by_id):
                cur = by_id[cur["parent_id"]]
                depth += 1
                hops += 1
            best = max(best, depth)
    return best


def fleet_bench(n_workers: int, groups: int = 64, group_size: int = 16,
                use_device: bool = False, devices=None,
                host_crossover: int | None = None,
                max_inflight_groups: int | None = 2,
                unique: int = 16, timeout_s: float = 600.0) -> dict:
    """Run ``groups`` signature groups of ``group_size`` ed25519 checks
    through an N-worker fleet and measure aggregate throughput + busy-time
    scaling efficiency. Returns the MULTICHIP artifact fields.

    Runs under a PRIVATE recording tracer (restored on exit) so the
    artifact can report ``stitched_trace_depth`` — proof the cross-process
    observability plane stitched node- and worker-side spans — without
    clobbering any tracer the host process installed."""
    prev_tracer = get_tracer()
    tracer = Tracer(capacity=16384)
    set_tracer(tracer)
    fleet = InProcessFleet(
        n_workers, use_device=use_device, devices=devices,
        host_crossover=host_crossover,
        max_inflight_groups=max_inflight_groups)
    try:
        checks = make_sig_checks(group_size, unique=unique)
        # warm the path (and, on device, the compile) before timing
        fleet.verify_signatures(checks).result(timeout=timeout_s)
        t0 = time.monotonic()
        futures = [fleet.verify_signatures(checks) for _ in range(groups)]
        for f in futures:
            f.result(timeout=timeout_s)
        makespan = time.monotonic() - t0
        total = groups * group_size
        busy = [max(0.0, (w.last_completion_t or t0) - t0)
                for w in fleet.workers]
        efficiency = (100.0 * (sum(busy) / len(busy)) / makespan
                      if makespan > 0 else 0.0)
        skew = (100.0 * (max(busy) - min(busy)) / makespan
                if makespan > 0 else 0.0)
        per_worker = {w.network_service.my_address: w.processed_sig_count
                      for w in fleet.workers}
        steals = fleet.steal_count()
        return {
            "fleet_verifies_per_sec": round(total / makespan, 1),
            "scaling_efficiency_pct": round(min(100.0, efficiency), 1),
            "worker_busy_skew_pct": round(max(0.0, min(100.0, skew)), 1),
            "n_workers": n_workers,
            "n_devices": len(devices) if devices is not None else 0,
            "fleet_steals": steals,
            "fleet_stolen": fleet.stolen_count(),
            "steals_total": steals,
            "stitched_trace_depth": stitched_trace_depth(
                tracer.ring.snapshot()),
            "groups": groups,
            "group_size": group_size,
            "wall_s": round(makespan, 4),
            "per_worker_sigs": per_worker,
        }
    finally:
        fleet.close()
        set_tracer(prev_tracer)
