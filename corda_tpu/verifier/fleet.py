"""In-process verifier fleet: N device-sharded workers behind one queue.

The MULTICHIP / ``bench.py --fleet`` harness. Everything rides the REAL
out-of-process protocol — ``OutOfProcessTransactionVerifierService``'s
load-aware router, ``VerifierWorker``'s stealable backlog, WorkerLoadReport
/ StealRequest / WorkReturned — but over the deterministic in-memory bus
with a background pump thread, so one process can measure fleet scaling
without spawning N OS processes (the TCP plane and
``python -m corda_tpu.verifier --num-shards`` are the production spelling
of the same topology).

Scaling efficiency is BUSY-TIME based, not count based::

    efficiency = 100 × mean_i(last_completion_i − t0) / makespan

i.e. how long each worker stayed busy relative to the whole run. A
count-based definition (total / (n × max_per_worker)) would punish
successful work stealing — stolen groups inflate the fast worker's count —
while busy-time rewards exactly what the fleet is for: nobody idles while
a straggler holds undone work.

The fleet is also the FleetController's substrate (``attach_controller``):
``add_worker`` / ``retire_worker`` are the scale actuators (riding
WorkerHello / graceful Goodbye), ``kill_worker`` simulates a crash for
the chaos harness (no Goodbye, no more load reports — only the stale
reaper or redelivery can recover its charged work), and the pump thread
doubles as the controller's tick loop. ``kill_storm_recovery`` is the
seeded proof: kill part of the fleet mid-load and measure the time back
to SLO-steady with zero lost futures.
"""
from __future__ import annotations

import random
import time
import threading
from concurrent.futures import TimeoutError as FutureTimeoutError

from ..core.crypto import generate_keypair
from ..core.crypto.schemes import EDDSA_ED25519_SHA512
from ..core.crypto.signatures import Crypto
from ..network.inmemory import InMemoryMessagingNetwork
from ..observability import Tracer, get_tracer, set_tracer
from ..utils.metrics import MetricRegistry
from .batcher import SignatureBatcher
from .out_of_process import (OutOfProcessTransactionVerifierService,
                             VerifierWorker, _weight)


def make_sig_checks(n: int, unique: int = 16, seed: int = 7):
    """Deterministic honestly-signed ed25519 ``(key, sig, content)`` checks,
    ``unique`` distinct tiled to ``n`` (the bench corpus shape — signing is
    pure Python, so uniqueness is bounded like bench.py's UNIQUE)."""
    base = []
    for i in range(min(n, unique)):
        entropy = (seed * 1000003 + i).to_bytes(32, "little")
        kp = generate_keypair(EDDSA_ED25519_SHA512, entropy=entropy)
        content = (seed * 999331 + i).to_bytes(64, "little")
        sig = Crypto.do_sign(kp.private, content, kp.public)
        base.append((kp.public, sig, content))
    return (base * (n // len(base) + 1))[:n]


class InProcessFleet:
    """N ``VerifierWorker``s (each with a private ``SignatureBatcher``,
    optionally pinned to one jax device) attached to one node-side service,
    all on an in-memory bus pumped by a background thread.

    ``report_every_s`` drives ``send_load_report`` from the pump thread —
    the load/steal machinery stays live without per-worker timer threads,
    and the pump delivers the reports in the same loop."""

    def __init__(self, n_workers: int, use_device: bool = False,
                 devices=None, host_crossover: int | None = None,
                 max_latency_s: float = 0.005,
                 max_inflight_groups: int | None = 2,
                 report_every_s: float = 0.01,
                 metrics: MetricRegistry | None = None):
        if n_workers < 1:
            raise ValueError("a fleet needs at least one worker")
        if devices is not None and len(devices) < n_workers:
            raise ValueError(f"{n_workers} workers but only "
                             f"{len(devices)} devices")
        self.n_workers = n_workers
        self.metrics = metrics if metrics is not None else MetricRegistry()
        self.bus = InMemoryMessagingNetwork()
        self.service = OutOfProcessTransactionVerifierService(
            self.bus.create_node("node"), metrics=self.metrics,
            expected_workers=n_workers,
            load_report_interval_s=report_every_s)
        self._batcher_kwargs: dict = {"use_device": use_device,
                                      "max_latency_s": max_latency_s}
        if host_crossover is not None:
            self._batcher_kwargs["host_crossover"] = host_crossover
        self._use_device = use_device
        self._devices = devices
        self._max_inflight_groups = max_inflight_groups
        self._workers_lock = threading.RLock()
        self.batchers: list[SignatureBatcher] = []
        self.workers: list[VerifierWorker] = []
        self.dead_workers: list[VerifierWorker] = []
        self._next_idx = 0
        for _ in range(n_workers):
            self._spawn_worker_locked()
        # controller plumbing (attach_controller): the SLO tracker fed by
        # verify_signatures outcomes, and the control loop the pump ticks
        self.slo = None
        self.controller = None
        self._controller_tick_s = report_every_s
        self._report_every_s = report_every_s
        self._stop = threading.Event()
        self._pump = threading.Thread(target=self._pump_loop, daemon=True,
                                      name="fleet-pump")
        self._pump.start()

    # -- worker lifecycle (the controller's scale actuators) -----------------
    def _spawn_worker_locked(self) -> VerifierWorker:
        i = self._next_idx
        self._next_idx += 1
        kwargs = dict(self._batcher_kwargs)
        shard: tuple = ()
        if self._devices is not None:
            dev = self._devices[i % len(self._devices)]
            kwargs["device"] = dev
            shard = (getattr(dev, "id", i),)
        batcher = SignatureBatcher(**kwargs)
        worker = VerifierWorker(
            self.bus.create_node(f"w{i}"), "node",
            batcher=batcher, use_device=self._use_device,
            device_shard=shard, capacity=1,
            load_report_interval_s=None,   # pump thread reports instead
            max_inflight_groups=self._max_inflight_groups)
        worker._report_enabled = True      # idle pings feed the stealer
        self.batchers.append(batcher)
        self.workers.append(worker)
        return worker

    def add_worker(self) -> str:
        """Spawn one more worker (controller scale-up): it attaches through
        the normal WorkerHello path on the next pump cycle. A worker
        spawned mid-degradation inherits the currently applied ladder
        rungs, so a join cannot undercut the shed."""
        with self._workers_lock:
            worker = self._spawn_worker_locked()
            if self.controller is not None:
                from .controller import apply_degradations
                apply_degradations(self.controller.ladder, worker._batcher)
            return worker.network_service.my_address

    def retire_worker(self) -> str | None:
        """Gracefully stop the newest worker (controller scale-down): its
        Goodbye detaches it and requeues anything it still held. Refuses
        to retire the last worker."""
        with self._workers_lock:
            if len(self.workers) <= 1:
                return None
            worker = self.workers.pop()
            self.dead_workers.append(worker)
        worker.stop(announce=True)
        return worker.network_service.my_address

    def kill_worker(self, name: str) -> str:
        """Chaos: crash one worker dead — no Goodbye, no further load
        reports — so its charged work hangs until the stale reaper
        crash-detaches it (the kill-storm recovery path)."""
        with self._workers_lock:
            worker = next(w for w in self.workers
                          if w.network_service.my_address == name)
            self.workers.remove(worker)
            self.dead_workers.append(worker)
        worker.stop(announce=False)
        return name

    def worker_names(self) -> list[str]:
        with self._workers_lock:
            return [w.network_service.my_address for w in self.workers]

    # -- controller wiring ---------------------------------------------------
    def attach_controller(self, slo=None, stale_detach_intervals: int = 5,
                          tick_every_s: float | None = None,
                          config=None):
        """Wire a FleetController onto this fleet: spawn/retire through
        the worker lifecycle above, stale reaping through the service, the
        degradation ladder over every worker batcher, and the pump thread
        as the tick loop. ``slo`` (an SLOTracker or None) is fed by
        ``verify_signatures`` outcomes from here on."""
        from .controller import FleetController, batcher_ladder
        if self.controller is not None:
            return self.controller
        self.slo = slo
        self.service.stale_detach_intervals = stale_detach_intervals
        self.controller = FleetController(
            slo=slo,
            worker_count=lambda: self.service.queue.worker_count,
            queue_depth=self._queue_signal,
            spawn=self.add_worker,
            retire=self.retire_worker,
            reap_stale=self.service.reap_stale_workers,
            breaker_open_count=self._open_breaker_count,
            ladder=batcher_ladder(self.batchers),
            config=config,
            metrics=self.metrics)
        self.service.controller = self.controller
        if tick_every_s is not None:
            self._controller_tick_s = tick_every_s
        return self.controller

    def _queue_signal(self) -> float:
        """Total estimated signature depth across the fleet (node-side
        pending + everything charged to workers) — the controller's
        queue-trend input."""
        q = self.service.queue
        with q._lock:
            pending = sum(_weight(r) for r in q._pending)
            dealt = sum(q._queue_depth_of(w) for w in q._workers)
        return float(pending + dealt)

    def _open_breaker_count(self) -> int:
        with self._workers_lock:
            batchers = [w._batcher for w in self.workers
                        if w._batcher is not None]
        n = 0
        for b in batchers:
            try:
                n += sum(1 for st in b.breaker_status().values()
                         if st.get("state") != "closed")
            except Exception:
                pass
        return n

    def _pump_loop(self) -> None:
        last_report = 0.0
        last_tick = 0.0
        while not self._stop.is_set():
            progressed = self.bus.run_network()
            now = time.monotonic()
            if now - last_report >= self._report_every_s:
                last_report = now
                with self._workers_lock:
                    workers = list(self.workers)
                for w in workers:
                    try:
                        w.send_load_report()
                    except Exception:
                        pass   # a stopped worker mid-close; pump survives
            ctl = self.controller
            if ctl is not None and now - last_tick >= self._controller_tick_s:
                last_tick = now
                try:
                    ctl.tick()
                except Exception:
                    pass   # a control hiccup must not kill the pump
            if not progressed:
                time.sleep(0.0005)

    def verify_signatures(self, checks):
        fut = self.service.verify_signatures(checks)
        if self.slo is not None:
            t0 = time.monotonic()

            def _record(f, t0=t0):
                try:
                    ok = f.exception() is None
                except Exception:
                    ok = False
                try:
                    self.slo.record(ok, time.monotonic() - t0)
                except Exception:
                    pass
            fut.add_done_callback(_record)
        return fut

    def steal_count(self) -> int:
        return self.metrics.meter("Fleet.Steals").count

    def stolen_count(self) -> int:
        return self.metrics.meter("Fleet.Stolen").count

    def close(self) -> None:
        self._stop.set()
        self._pump.join(timeout=5.0)
        with self._workers_lock:
            everyone = list(self.workers) + list(self.dead_workers)
        for w in everyone:
            try:
                w.stop(announce=False)
            except Exception:
                pass
        for b in self.batchers:
            b.close()
        self.service.shutdown()


def stitched_trace_depth(spans) -> int:
    """Deepest parent chain among traces that contain BOTH a node-side
    ``verifier.oop_submit`` span and at least one ``worker.*`` span — i.e.
    traces that actually crossed the process seam. 0 means no stitched
    trace existed (the cross-process plane was dark)."""
    by_trace: dict[str, list[dict]] = {}
    for s in spans:
        if isinstance(s, dict) and s.get("trace_id"):
            by_trace.setdefault(s["trace_id"], []).append(s)
    best = 0
    for group in by_trace.values():
        names = [s.get("name") or "" for s in group]
        if ("verifier.oop_submit" not in names
                or not any(n.startswith("worker.") for n in names)):
            continue
        by_id = {s["span_id"]: s for s in group if s.get("span_id")}
        for s in group:
            depth, cur, hops = 1, s, 0
            while cur.get("parent_id") in by_id and hops < len(by_id):
                cur = by_id[cur["parent_id"]]
                depth += 1
                hops += 1
            best = max(best, depth)
    return best


def fleet_bench(n_workers: int, groups: int = 64, group_size: int = 16,
                use_device: bool = False, devices=None,
                host_crossover: int | None = None,
                max_inflight_groups: int | None = 2,
                unique: int = 16, timeout_s: float = 600.0) -> dict:
    """Run ``groups`` signature groups of ``group_size`` ed25519 checks
    through an N-worker fleet and measure aggregate throughput + busy-time
    scaling efficiency. Returns the MULTICHIP artifact fields.

    Runs under a PRIVATE recording tracer (restored on exit) so the
    artifact can report ``stitched_trace_depth`` — proof the cross-process
    observability plane stitched node- and worker-side spans — without
    clobbering any tracer the host process installed.

    A FleetController rides along in OBSERVE trim (no SLO tracker,
    infinite queue thresholds, scale range pinned to ``n_workers``): an
    unstressed bench must report ``controller_state == "steady"`` with
    zero actions, and that invariant is asserted by the smoke gate — a
    controller that acts on a healthy fleet is a regression."""
    from .controller import ControllerConfig
    prev_tracer = get_tracer()
    tracer = Tracer(capacity=16384)
    set_tracer(tracer)
    fleet = InProcessFleet(
        n_workers, use_device=use_device, devices=devices,
        host_crossover=host_crossover,
        max_inflight_groups=max_inflight_groups)
    ctl = fleet.attach_controller(
        slo=None, stale_detach_intervals=50,
        config=ControllerConfig(
            min_workers=n_workers, max_workers=n_workers,
            queue_high=float("inf"), queue_low=float("inf"),
            breakers_stress=False))
    try:
        checks = make_sig_checks(group_size, unique=unique)
        # warm the path (and, on device, the compile) before timing
        fleet.verify_signatures(checks).result(timeout=timeout_s)
        t0 = time.monotonic()
        futures = [fleet.verify_signatures(checks) for _ in range(groups)]
        for f in futures:
            f.result(timeout=timeout_s)
        makespan = time.monotonic() - t0
        total = groups * group_size
        busy = [max(0.0, (w.last_completion_t or t0) - t0)
                for w in fleet.workers]
        efficiency = (100.0 * (sum(busy) / len(busy)) / makespan
                      if makespan > 0 else 0.0)
        skew = (100.0 * (max(busy) - min(busy)) / makespan
                if makespan > 0 else 0.0)
        per_worker = {w.network_service.my_address: w.processed_sig_count
                      for w in fleet.workers}
        steals = fleet.steal_count()
        ctl_status = ctl.status()
        return {
            "fleet_verifies_per_sec": round(total / makespan, 1),
            "scaling_efficiency_pct": round(min(100.0, efficiency), 1),
            "worker_busy_skew_pct": round(max(0.0, min(100.0, skew)), 1),
            "n_workers": n_workers,
            "n_devices": len(devices) if devices is not None else 0,
            "fleet_steals": steals,
            "fleet_stolen": fleet.stolen_count(),
            "steals_total": steals,
            "stitched_trace_depth": stitched_trace_depth(
                tracer.ring.snapshot()),
            "groups": groups,
            "group_size": group_size,
            "wall_s": round(makespan, 4),
            "per_worker_sigs": per_worker,
            "controller_state": ctl_status["state"],
            "controller_actions": ctl_status["actions_total"],
            "recovery_s": ctl_status["recovery_s_last"] or 0.0,
        }
    finally:
        fleet.close()
        set_tracer(prev_tracer)


def kill_storm_recovery(n_workers: int = 3, seed: int = 7,
                        groups: int = 60, group_size: int = 6,
                        kill_fraction: float = 0.5,
                        slo_windows_s: tuple = (0.5, 2.0),
                        latency_slo_ms: float = 250.0,
                        timeout_s: float = 60.0) -> dict:
    """Seeded kill-storm: crash ~``kill_fraction`` of the fleet mid-load
    and measure the controller-driven recovery. The SLO burns while the
    dead workers' charged futures wait out the stale horizon; the
    controller crash-detaches the corpses (requeue → survivors), spawns
    replacements, and the episode closes when the fleet holds a healthy
    streak again.

    The recovery bound is ERROR-BUDGET based: the long burn window
    (``slo_windows_s[-1]``) is where the budget was burned, and each
    phase of a real recovery is bounded by one such window — the stale
    horizon before the corpses are detached, the requeued-work drain on
    the survivors, the aging-out of the last bad events, and the
    healthy-streak hysteresis — so a controller that actually restored
    service must be back to steady within 4× that window.
    Returns the artifact/assertion fields; ``lost_futures`` must be 0
    and ``recovered_within_bound`` True for the chaos gate to pass."""
    from ..observability.slo import SLObjective, SLOTracker
    from .controller import ControllerConfig
    prev_tracer = get_tracer()
    tracer = Tracer(capacity=16384)
    set_tracer(tracer)
    rng = random.Random(seed)
    slo = SLOTracker(
        objectives=(SLObjective("availability", 0.999),
                    SLObjective("latency_p99", 0.95,
                                latency_ms=latency_slo_ms)),
        windows_s=slo_windows_s)
    fleet = InProcessFleet(n_workers, use_device=False,
                           report_every_s=0.02)
    ctl = fleet.attach_controller(
        slo=slo, stale_detach_intervals=8,
        config=ControllerConfig(
            min_workers=n_workers, max_workers=n_workers + 2,
            scale_cooldown_s=0.25, step_cooldown_s=0.25,
            # 10 ticks × 0.02 s = 200 ms of sustained health before any
            # reversal: a shorter streak lets a mid-storm lull close the
            # episode early and a second one open, splitting the timeline
            healthy_ticks=10))
    lost = failed = 0
    killed: list[str] = []
    try:
        checks = make_sig_checks(group_size, seed=seed)
        fleet.verify_signatures(checks).result(timeout=timeout_s)  # warm
        futures = []
        kill_at = max(1, groups // 4)
        for i in range(groups):
            futures.append(fleet.verify_signatures(checks))
            if i == kill_at:
                live = fleet.worker_names()
                n_kill = max(1, int(round(len(live) * kill_fraction)))
                for name in rng.sample(live, n_kill):
                    killed.append(fleet.kill_worker(name))
            time.sleep(0.001 + rng.random() * 0.002)
        for f in futures:
            try:
                if f.result(timeout=timeout_s) is not None:
                    failed += 1
            except FutureTimeoutError:
                lost += 1   # a future that never resolved: the real crime
            except Exception:
                failed += 1
        bound_s = 4.0 * slo_windows_s[-1]
        deadline = time.monotonic() + bound_s
        while time.monotonic() < deadline and ctl.state != "steady":
            time.sleep(0.02)
        st = ctl.status()
        spans = tracer.ring.snapshot()
        episodes = [s for s in spans
                    if s.get("name") == "controller.episode"]
        ep_ids = {s["span_id"] for s in episodes}
        annotated = [s for s in spans
                     if (s.get("name") or "").startswith("controller.")
                     and s.get("parent_id") in ep_ids]
        recovery = st["recovery_s_last"]
        return {
            "seed": seed,
            "n_workers": n_workers,
            "killed_workers": killed,
            "groups": groups,
            "group_size": group_size,
            "lost_futures": lost,
            "failed_futures": failed,
            "controller_actions": st["actions_total"],
            "controller_state": st["state"],
            "recovery_s": (round(recovery, 3)
                           if recovery is not None else None),
            "recovery_bound_s": round(bound_s, 3),
            "recovered_within_bound": (st["state"] == "steady"
                                       and recovery is not None
                                       and recovery <= bound_s),
            "episode_spans": len(episodes),
            "episode_action_spans": len(annotated),
        }
    finally:
        fleet.close()
        set_tracer(prev_tracer)
