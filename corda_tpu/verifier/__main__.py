"""Standalone verifier worker process — ``python -m corda_tpu.verifier``.

Reference parity: Verifier.main (verifier/src/main/.../Verifier.kt:42-79) —
a leaf process that attaches to a node's verification queue, consumes
requests, verifies, replies. Stateless: run N copies against one queue;
killing one redistributes its outstanding work (the node's redelivery
timeout or Goodbye handling, VerifierTests.kt:73+).

TPU-first: the worker runs the signature EC math through its own
``SignatureBatcher`` device kernels — consecutive requests' signatures
coalesce into one device batch, so N worker processes = N chips of
cross-transaction batched verification behind one competing-consumer queue.

Prints ``VERIFIER READY <host>:<port>`` on stdout once attached (the driver
DSL's readiness handshake, like the node's NODE READY line). On SIGTERM it
writes batcher metrics to ``--stats-file`` (if given) so tests can assert
device-verified work happened in this process, then exits cleanly.
"""
from __future__ import annotations

import argparse
import importlib
import json
import os
import signal
import sys
import threading


def _literal_resolve(name: str):
    """Workers address peers only as literal "host:port" strings."""
    host, _, port = name.rpartition(":")
    try:
        return host, int(port)
    except ValueError:
        return None


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="corda-tpu-verifier")
    parser.add_argument("--queue-address", required=True,
                        help="host:port of the node whose queue to consume")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--no-device", action="store_true",
                        help="host-only verification (no kernels)")
    parser.add_argument("--host-crossover", type=int, default=None,
                        help="batches below this run on host (default: "
                             "the batcher's measured crossover)")
    parser.add_argument("--mesh-devices", type=int, default=None,
                        help="shard device batches over the first N local "
                             "chips (jax.sharding.Mesh; Verifier.kt's "
                             "scale-out seam, SPMD instead of N processes)")
    parser.add_argument("--num-shards", type=int, default=None,
                        help="fleet mode: split the visible devices into N "
                             "contiguous shards; this worker takes shard "
                             "--shard-index (run N workers, one per shard)")
    parser.add_argument("--shard-index", type=int, default=0,
                        help="which device shard this worker owns "
                             "(with --num-shards)")
    parser.add_argument("--capacity", type=int, default=None,
                        help="advertised relative capacity (default: the "
                             "shard's device count; the node router "
                             "normalizes load estimates by it)")
    parser.add_argument("--load-report-interval", type=float, default=0.5,
                        help="seconds between WorkerLoadReports to the node "
                             "router (0 disables)")
    parser.add_argument("--stats-file",
                        help="write batcher metrics JSON here on shutdown")
    parser.add_argument("--cordapp", action="append", default=None,
                        help="modules to import so contract/state types "
                             "deserialize (default: corda_tpu.finance + "
                             "corda_tpu.testing.dummy)")
    args = parser.parse_args(argv)

    for module in (args.cordapp if args.cordapp is not None
                   else ["corda_tpu.finance", "corda_tpu.testing.dummy"]):
        importlib.import_module(module)

    # persistent compile cache: repeated worker launches must not re-pay the
    # kernel compiles (jax.config.update is the reliable path — the env-var
    # spelling is not honored by all versions)
    cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if cache_dir:
        import jax
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from ..network.tcp import TcpMessagingService
    from .batcher import SignatureBatcher
    from .out_of_process import VerifierWorker

    messaging = TcpMessagingService("verifier-worker", args.host, args.port,
                                    _literal_resolve)
    # the worker's reachable address IS its identity: the node replies and
    # deals work to exactly this host:port (no network-map registration,
    # same as the reference worker attaching straight to the broker)
    messaging._name = f"{args.host}:{messaging.port}"

    batcher_kwargs = {"use_device": not args.no_device}
    if args.host_crossover is not None:
        batcher_kwargs["host_crossover"] = args.host_crossover
    device_shard: tuple = ()
    if args.mesh_devices is not None and args.num_shards is not None:
        parser.error("--mesh-devices and --num-shards are exclusive: a "
                     "fleet worker owns a device shard, not the whole mesh")
    if args.mesh_devices is not None:
        from ..parallel import make_mesh
        batcher_kwargs["mesh"] = make_mesh(args.mesh_devices)
    elif args.num_shards is not None and not args.no_device:
        # fleet mode: this worker owns one contiguous shard of the visible
        # devices — a private mesh when the shard has several chips, a
        # plain device pin (no shard_map overhead) when it has one
        from ..parallel import shard_devices
        shard = shard_devices(args.num_shards)[args.shard_index]
        device_shard = tuple(d.id for d in shard)
        if len(shard) > 1:
            from ..parallel import make_mesh
            batcher_kwargs["mesh"] = make_mesh(devices=shard)
        else:
            batcher_kwargs["device"] = shard[0]
    batcher = SignatureBatcher(**batcher_kwargs)
    worker = VerifierWorker(
        messaging, args.queue_address, batcher=batcher,
        use_device=not args.no_device,
        hello_interval_s=3.0,
        device_shard=device_shard, capacity=args.capacity,
        load_report_interval_s=(args.load_report_interval
                                if args.load_report_interval > 0 else None))

    print(f"VERIFIER READY {args.host}:{messaging.port}", flush=True)

    done = threading.Event()

    def _shutdown(signum, frame):
        done.set()

    signal.signal(signal.SIGTERM, _shutdown)
    signal.signal(signal.SIGINT, _shutdown)
    done.wait()

    if args.stats_file:
        snap = batcher.metrics.snapshot()
        with open(args.stats_file, "w") as f:
            json.dump({"verified_count": worker.verified_count,
                       "processed_sig_count": worker.processed_sig_count,
                       "device_shard": list(worker.device_shard),
                       "metrics": snap}, f)
    worker.stop()
    messaging.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
