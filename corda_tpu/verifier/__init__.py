"""Transaction verification services — the north-star seam.

Reference parity: `TransactionVerifierService` (Services.kt:544-550, async
`verify(ltx) → future`), `InMemoryTransactionVerifierService` (4-thread pool),
and the out-of-process verifier fan-out (Verifier.kt, VerifierApi.kt) — here
re-designed TPU-first: per-signature EC verification and Merkle hashing are
batched across MANY transactions into device kernels; contract `verify()`
bodies and coverage checks stay on host.
"""
from .batcher import SignatureBatcher  # noqa: F401
from .service import (  # noqa: F401
    InMemoryTransactionVerifierService,
    TpuTransactionVerifierService,
    TransactionVerifierService,
    make_verifier_service,
)
