"""Cash flows: issue, pay, exit.

Reference parity: finance/.../flows/CashIssueFlow.kt, CashPaymentFlow.kt,
CashExitFlow.kt (thin flows over the Cash contract's builder helpers +
FinalityFlow, with vault coin selection and soft locking for payments).
"""
from __future__ import annotations

from ..core.contracts.amount import Amount
from ..core.contracts.structures import PartyAndReference
from ..core.transactions.builder import TransactionBuilder
from ..flows.api import FlowException, FlowLogic, initiating_flow, startable_by_rpc
from ..flows.library import FinalityFlow
from .cash import Cash, CashState, InsufficientBalanceException


@startable_by_rpc
@initiating_flow
class CashIssueFlow(FlowLogic):
    """Issue `amount` of our own currency to `recipient`, notarised by
    `notary` (CashIssueFlow.kt)."""

    def __init__(self, amount: Amount, issuer_ref: bytes, recipient, notary):
        self.amount = amount
        self.issuer_ref = issuer_ref
        self.recipient = recipient
        self.notary = notary

    def call(self):
        me = self.service_hub.my_info.legal_identity
        builder = TransactionBuilder(notary=self.notary)
        Cash.generate_issue(builder, self.amount,
                            PartyAndReference(me, self.issuer_ref),
                            self.recipient.owning_key, self.notary)
        builder.sign_with(self.service_hub.key_management.key_pair(me.owning_key))
        stx = builder.to_signed_transaction(check_sufficient_signatures=False)
        final = yield from self.sub_flow(FinalityFlow(stx, [self.recipient]))
        return final


@startable_by_rpc
@initiating_flow
class CashPaymentFlow(FlowLogic):
    """Pay `amount` to `recipient` from our vault (CashPaymentFlow.kt):
    coin-select + soft-lock, build the move, sign, finalise."""

    def __init__(self, amount: Amount, recipient):
        self.amount = amount
        self.recipient = recipient

    def call(self):
        # Coin selection reads mutable vault state → must execute exactly once
        # and be checkpointed, or a restart would rebuild a DIFFERENT spend
        # than the one already sent for notarisation (flows.api.ExecuteOnce).
        stx = yield from self.record(self._build_spend)
        final = yield from self.sub_flow(FinalityFlow(stx, [self.recipient]))
        return final

    def _build_spend(self):
        hub = self.service_hub
        me = hub.my_info.legal_identity
        lock_id = self.run_id or "payment"
        coins = hub.vault.try_lock_states_for_spending(
            lock_id, self.amount.quantity, CashState,
            quantity_of=lambda s: s.amount.quantity,
            state_filter=lambda s: s.amount.token.product == self.amount.token)
        if not coins:
            raise FlowException(f"Insufficient cash to pay {self.amount}")
        try:
            builder = TransactionBuilder()
            Cash.generate_spend(builder, self.amount,
                                self.recipient.owning_key, coins,
                                change_owner=me.owning_key)
            builder.sign_with(hub.key_management.key_pair(me.owning_key))
            return builder.to_signed_transaction(check_sufficient_signatures=False)
        except InsufficientBalanceException as e:
            hub.vault.soft_lock_release(lock_id)
            raise FlowException(str(e)) from e
        except Exception:
            hub.vault.soft_lock_release(lock_id)
            raise


@startable_by_rpc
@initiating_flow
class CashExitFlow(FlowLogic):
    """Remove `amount` of our issued cash from the ledger (CashExitFlow.kt)."""

    def __init__(self, amount: Amount, issuer_ref: bytes):
        self.amount = amount
        self.issuer_ref = issuer_ref

    def call(self):
        stx = yield from self.record(self._build_exit)  # vault read: see above
        final = yield from self.sub_flow(FinalityFlow(stx))
        return final

    def _build_exit(self):
        from ..core.contracts.structures import Issued
        from .cash import Exit, Move
        hub = self.service_hub
        me = hub.my_info.legal_identity
        issued_token = Issued(PartyAndReference(me, self.issuer_ref),
                              self.amount.token)
        coins = [sar for sar in hub.vault.unconsumed_states(CashState)
                 if sar.state.data.amount.token == issued_token]
        gathered, used = 0, []
        for sar in coins:
            used.append(sar)
            gathered += sar.state.data.amount.quantity
            if gathered >= self.amount.quantity:
                break
        if gathered < self.amount.quantity:
            raise FlowException(f"Insufficient cash to exit {self.amount}")
        builder = TransactionBuilder()
        for sar in used:
            builder.add_input_state(sar)
        if gathered > self.amount.quantity:
            builder.add_output_state(CashState(
                Amount(gathered - self.amount.quantity, issued_token),
                me.owning_key), used[0].state.notary)
        exit_amount = Amount(self.amount.quantity, issued_token)
        builder.add_command(Exit(exit_amount), me.owning_key)
        # conservation is enforced by the Move clause (inputs = outputs + exit)
        builder.add_command(Move(), me.owning_key)
        builder.sign_with(hub.key_management.key_pair(me.owning_key))
        return builder.to_signed_transaction(check_sufficient_signatures=False)
