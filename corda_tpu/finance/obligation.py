"""Obligation — bilateral debt with settlement, netting and default.

Reference parity: finance/.../contracts/Obligation.kt:1-727, scoped to its
core semantics:

- `ObligationState(obligor, template, quantity, beneficiary)` — obligor owes
  beneficiary `quantity` of the template's product by the due time.
- Issue: creates debt, signed by the obligor (you can only bind yourself).
- Move: transfers the claim to a new beneficiary, signed by the current one;
  per-group conservation.
- Settle: extinguishes debt against cash actually paid to the beneficiary in
  the same transaction.
- Net: obligations in OPPOSITE directions on the same template cancel —
  the pairwise net position is preserved, everyone involved signs
  (the bilateral netting Obligation.kt:360+ implements).
- SetLifecycle: flips NORMAL <-> DEFAULTED after the due time, at the
  beneficiary's signature.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass

from ..core.contracts.amount import Amount
from ..core.contracts.clauses import (AnyOf, Clause, GroupClauseVerifier,
                                      verify_clause)
from ..core.contracts.exceptions import TransactionVerificationException
from ..core.contracts.structures import (CommandData, Contract, Issued,
                                         TypeOnlyCommandData)
from ..core.crypto.keys import PublicKey
from ..core.crypto.secure_hash import SecureHash
from ..core.serialization import register_type, serializable
from .cash import CashState


@serializable("Obligation.Lifecycle")
class Lifecycle(enum.Enum):
    NORMAL = "NORMAL"
    DEFAULTED = "DEFAULTED"


@serializable("Obligation.Terms")
@dataclass(frozen=True)
class Terms:
    """What is owed and by when (Obligation.Terms): the acceptable settlement
    token and the due time (epoch micros)."""

    product: object          # Issued[Currency]
    due_before_micros: int


@serializable("Obligation.Issue")
@dataclass(frozen=True)
class Issue(TypeOnlyCommandData):
    pass


@serializable("Obligation.Move")
@dataclass(frozen=True)
class Move(TypeOnlyCommandData):
    pass


@serializable("Obligation.Settle")
@dataclass(frozen=True)
class Settle(CommandData):
    amount_quantity: int


@serializable("Obligation.Net")
@dataclass(frozen=True)
class Net(TypeOnlyCommandData):
    pass


@serializable("Obligation.SetLifecycle")
@dataclass(frozen=True)
class SetLifecycle(CommandData):
    lifecycle: Lifecycle


@serializable("Obligation.State")
@dataclass(frozen=True)
class ObligationState:
    obligor: PublicKey
    template: Terms
    quantity: int
    beneficiary: PublicKey
    lifecycle: Lifecycle = Lifecycle.NORMAL

    @property
    def contract(self) -> "Obligation":
        return OBLIGATION_PROGRAM

    @property
    def participants(self):
        return [self.obligor, self.beneficiary]

    @property
    def amount(self) -> Amount:
        return Amount(self.quantity, self.template.product)

    def with_new_beneficiary(self, new_beneficiary: PublicKey):
        return (Move(), ObligationState(self.obligor, self.template,
                                        self.quantity, new_beneficiary,
                                        self.lifecycle))


def _pair_positions(states) -> dict:
    """(obligor, beneficiary) → total quantity. The netting invariant works
    on the antisymmetric difference of these."""
    out: dict = {}
    for s in states:
        key = (s.obligor, s.beneficiary)
        out[key] = out.get(key, 0) + s.quantity
    return out


def _net_positions(states) -> dict:
    """Unordered-pair → signed net quantity (a<b ordering fixes the sign)."""
    out: dict = {}
    for (obligor, beneficiary), qty in _pair_positions(states).items():
        a, b = sorted((obligor, beneficiary))
        sign = 1 if obligor == a else -1
        key = (a, b)
        out[key] = out.get(key, 0) + sign * qty
    return {k: v for k, v in out.items() if v != 0}


def _lifecycle_pair_positions(states) -> dict:
    """(obligor, beneficiary, lifecycle) → total quantity: the full identity
    of a claim. Clauses account per ENTRY so no state's debtor, creditor or
    default status can silently change under an unrelated command."""
    out: dict = {}
    for s in states:
        key = (s.obligor, s.beneficiary, s.lifecycle)
        out[key] = out.get(key, 0) + s.quantity
    return out


class IssueClause(Clause):
    required_commands = (Issue,)

    def verify(self, tx, inputs, outputs, commands, key) -> set:
        cmds = [c for c in commands if isinstance(c.value, Issue)]
        if not cmds:
            return set()
        in_pos = _lifecycle_pair_positions(inputs)
        out_pos = _lifecycle_pair_positions(outputs)
        signers = {k for c in cmds for k in c.signers}
        increased = False
        # per-claim accounting: nothing may shrink (that would destroy someone
        # else's claim); growth needs that claim's obligor signature
        for entry in set(in_pos) | set(out_pos):
            delta = out_pos.get(entry, 0) - in_pos.get(entry, 0)
            if delta < 0:
                raise TransactionVerificationException(
                    tx.id, "An issuance may not reduce any existing claim")
            if delta > 0:
                increased = True
                obligor, _, lifecycle = entry
                if lifecycle != Lifecycle.NORMAL:
                    raise TransactionVerificationException(
                        tx.id, "New debt must be issued in the NORMAL lifecycle")
                if not obligor.is_fulfilled_by(signers):
                    raise TransactionVerificationException(
                        tx.id, "Issue must be signed by the obligor "
                               "(only you can bind yourself into debt)")
        if not increased:
            raise TransactionVerificationException(
                tx.id, "An obligation issuance must increase the amount owed")
        return {c.value for c in cmds}


class MoveClause(Clause):
    required_commands = (Move,)

    def verify(self, tx, inputs, outputs, commands, key) -> set:
        cmds = [c for c in commands if isinstance(c.value, Move)]
        if not cmds:
            return set()
        # per (obligor, lifecycle): only the beneficiary column may change —
        # a move can neither change who owes nor flip defaults
        def by_obligor_lifecycle(states):
            out: dict = {}
            for s in states:
                k = (s.obligor, s.lifecycle)
                out[k] = out.get(k, 0) + s.quantity
            return out

        if by_obligor_lifecycle(inputs) != by_obligor_lifecycle(outputs):
            raise TransactionVerificationException(
                tx.id, "A move may not change who owes the debt, its amount, "
                       "or its lifecycle")
        signers = {k for c in cmds for k in c.signers}
        for s in inputs:
            if not s.beneficiary.is_fulfilled_by(signers):
                raise TransactionVerificationException(
                    tx.id, "Move must be signed by the current beneficiary")
        return {c.value for c in cmds}


class SettleClause(Clause):
    required_commands = (Settle,)

    def verify(self, tx, inputs, outputs, commands, key) -> set:
        cmds = [c for c in commands if isinstance(c.value, Settle)]
        if not cmds:
            return set()
        settled = sum(c.value.amount_quantity for c in cmds)
        in_pos = _lifecycle_pair_positions(inputs)
        out_pos = _lifecycle_pair_positions(outputs)
        reductions: dict = {}
        for entry in set(in_pos) | set(out_pos):
            delta = in_pos.get(entry, 0) - out_pos.get(entry, 0)
            if delta < 0:
                raise TransactionVerificationException(
                    tx.id, "A settlement may not create new claims")
            if delta > 0:
                reductions[entry] = delta
        if sum(reductions.values()) != settled:
            raise TransactionVerificationException(
                tx.id, f"Settlement amounts must balance: reductions "
                       f"{sum(reductions.values())} vs {settled} declared")
        signers = {k for c in cmds for k in c.signers}
        for (obligor, _, _) in reductions:
            if not obligor.is_fulfilled_by(signers):
                raise TransactionVerificationException(
                    tx.id, "Settle must be signed by the obligor")
        # per-beneficiary cash adequacy is checked GLOBALLY across groups in
        # Obligation.verify (one cash output can't double-count, and
        # multi-beneficiary settlements are judged jointly)
        return {c.value for c in cmds}


class NetClause(Clause):
    required_commands = (Net,)

    def verify(self, tx, inputs, outputs, commands, key) -> set:
        cmds = [c for c in commands if isinstance(c.value, Net)]
        if not cmds:
            return set()
        if _net_positions(inputs) != _net_positions(outputs):
            raise TransactionVerificationException(
                tx.id, "Netting must preserve every pairwise net position")
        signers = {k for c in cmds for k in c.signers}
        # consent from everyone whose claims appear on EITHER side — a
        # zero-net pair of fabricated opposite obligations still binds its
        # parties (default exposure) and needs their signatures
        involved = {p for s in list(inputs) + list(outputs)
                    for p in (s.obligor, s.beneficiary)}
        for party_key in involved:
            if not party_key.is_fulfilled_by(signers):
                raise TransactionVerificationException(
                    tx.id, "Netting requires signatures from every party "
                           "whose obligations are netted")
        return {c.value for c in cmds}


class SetLifecycleClause(Clause):
    required_commands = (SetLifecycle,)

    def verify(self, tx, inputs, outputs, commands, key) -> set:
        cmds = [c for c in commands if isinstance(c.value, SetLifecycle)]
        if not cmds:
            return set()
        if len(inputs) != len(outputs):
            raise TransactionVerificationException(
                tx.id, "Lifecycle changes must keep every obligation")
        target = cmds[0].value.lifecycle
        from ..core.contracts.structures import tx_time_micros
        t = tx_time_micros(tx)
        for inp, out in zip(sorted(inputs, key=repr),
                            sorted(outputs, key=repr)):
            unchanged = ObligationState(inp.obligor, inp.template,
                                        inp.quantity, inp.beneficiary, target)
            if out != unchanged:
                raise TransactionVerificationException(
                    tx.id, "Lifecycle change must alter only the lifecycle")
            if target == Lifecycle.DEFAULTED:
                if t is None or t < inp.template.due_before_micros:
                    raise TransactionVerificationException(
                        tx.id, "Cannot default an obligation before it is due")
        signers = {k for c in cmds for k in c.signers}
        for s in inputs:
            if not s.beneficiary.is_fulfilled_by(signers):
                raise TransactionVerificationException(
                    tx.id, "Lifecycle change must be signed by the beneficiary")
        return {c.value for c in cmds}


class ObligationGroupClause(GroupClauseVerifier):
    def __init__(self):
        super().__init__(AnyOf(IssueClause(), MoveClause(), SettleClause(),
                               NetClause(), SetLifecycleClause()))

    def group_states(self, tx):
        return tx.group_states(ObligationState, lambda s: s.template)


class Obligation(Contract):
    legal_contract_reference = SecureHash.sha256(
        b"corda_tpu.finance.Obligation: bilateral nettable debt")

    Issue = Issue
    Move = Move
    Settle = Settle
    Net = Net
    SetLifecycle = SetLifecycle
    State = ObligationState
    Lifecycle = Lifecycle
    Terms = Terms

    def verify(self, tx) -> None:
        ob_commands = [c for c in tx.commands
                       if isinstance(c.value, (Issue, Move, Settle, Net,
                                               SetLifecycle))]
        if any(isinstance(c.value, Settle) for c in ob_commands):
            self._verify_settlement_cash(tx)
        verify_clause(tx, ObligationGroupClause(), ob_commands)

    @staticmethod
    def _verify_settlement_cash(tx) -> None:
        """Global cash adequacy: for every (beneficiary, product), the cash
        paid must cover the TOTAL debt reduction across all obligation groups
        — per-group checks would let one cash output double-count against
        obligations under different terms (same product, different due dates),
        and would wrongly reject multi-beneficiary settlements."""
        reduced: dict = {}
        for s in tx.inputs:
            if isinstance(s, ObligationState):
                k = (s.beneficiary, s.template.product)
                reduced[k] = reduced.get(k, 0) + s.quantity
        for s in tx.outputs:
            if isinstance(s, ObligationState):
                k = (s.beneficiary, s.template.product)
                reduced[k] = reduced.get(k, 0) - s.quantity
        for (beneficiary, product), owed_drop in reduced.items():
            if owed_drop <= 0:
                continue
            paid = sum(o.amount.quantity for o in tx.outputs
                       if isinstance(o, CashState)
                       and o.owner == beneficiary
                       and o.amount.token == product)
            if paid < owed_drop:
                raise TransactionVerificationException(
                    tx.id, f"Settlement must pay the beneficiary in the "
                           f"obligation's product ({paid} paid vs "
                           f"{owed_drop} extinguished)")


OBLIGATION_PROGRAM = Obligation()

register_type("Obligation", Obligation, to_fields=lambda c: [],
              from_fields=lambda f: OBLIGATION_PROGRAM)
