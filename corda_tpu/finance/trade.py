"""TwoPartyTradeFlow — atomic delivery-versus-payment.

Reference parity: finance/.../flows/TwoPartyTradeFlow.kt:1-206 — Seller offers
an asset for a cash price; Buyer resolves and inspects the asset, assembles
the swap transaction (asset→buyer leg + cash→seller leg), part-signs it and
returns it; Seller checks and signs, then notarises and broadcasts through
FinalityFlow. Either side walks away before signatures are exchanged and
nothing moves — the atomicity the reference's test suite drills (including
mid-flow node restarts, TwoPartyTradeFlowTests.kt:715).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from ..core.contracts.amount import Amount
from ..core.serialization import register_type
from ..core.transactions.builder import TransactionBuilder
from ..core.transactions.signed import SignedTransaction
from ..flows.api import (FlowException, FlowLogic, Receive, Send,
                         SendAndReceive, initiated_by, initiating_flow)
from ..flows.library import FinalityFlow, ResolveTransactionsFlow
from .cash import Cash, CashState


@dataclass(frozen=True)
class SellerTradeInfo:
    """The seller's opening offer (TwoPartyTradeFlow.SellerTradeInfo)."""

    asset_for_sale: Any     # StateAndRef
    price: Amount           # Amount[Currency]
    seller_owner_key: Any   # PublicKey the cash leg must pay


register_type("trade.SellerTradeInfo", SellerTradeInfo)


@initiating_flow
class SellerFlow(FlowLogic):
    def __init__(self, buyer, asset_ref, price: Amount):
        self.buyer = buyer
        self.asset_ref = asset_ref
        self.price = price

    def call(self):
        hub = self.service_hub
        me = hub.my_info.legal_identity
        offer = SellerTradeInfo(self.asset_ref, self.price, me.owning_key)
        resp = yield SendAndReceive(self.buyer, offer, SignedTransaction)

        def validate(ptx):
            if not isinstance(ptx, SignedTransaction):
                raise FlowException("Expected the buyer's partial transaction")
            wtx = ptx.tx
            # our asset must be an input, and the cash leg must pay us in full
            if self.asset_ref.ref not in wtx.inputs:
                raise FlowException("Proposed transaction does not consume the asset")
            paid = sum(o.data.amount.quantity for o in wtx.outputs
                       if isinstance(o.data, CashState)
                       and o.data.owner == me.owning_key
                       and o.data.amount.token.product == self.price.token)
            if paid < self.price.quantity:
                raise FlowException(
                    f"Proposed transaction pays {paid}, price is "
                    f"{self.price.quantity}")
            # buyer must have signed already (their cash inputs demand it)
            ptx.check_signatures_are_valid()
            return ptx

        ptx = resp.unwrap(validate)
        # resolve the buyer's cash chain from the buyer before signing —
        # the seller finalises, so a validating notary resolves the swap's
        # FULL dependency graph from the seller (TwoPartyTradeFlow.kt's
        # SignTransactionFlow performs exactly this resolution)
        yield from self.sub_flow(ResolveTransactionsFlow(
            self.buyer, stx=ptx))
        stx = ptx.plus(hub.sign(ptx.id.bytes, me.owning_key))
        final = yield from self.sub_flow(FinalityFlow(stx, [self.buyer]))
        return final


@initiated_by(SellerFlow)
class BuyerFlow(FlowLogic):
    """Assembles the swap: asset to us, price in cash to the seller. Business
    acceptance policy lives in `check_offer` (override to be pickier)."""

    def __init__(self, seller):
        self.seller = seller

    def check_offer(self, info: SellerTradeInfo) -> None:
        """Override for price/asset acceptance checks; raise to refuse."""

    def call(self):
        hub = self.service_hub
        me = hub.my_info.legal_identity
        req = yield Receive(self.seller, SellerTradeInfo)
        info = req.unwrap(lambda r: r if isinstance(r, SellerTradeInfo)
                          else _refuse())
        self.check_offer(info)
        # resolve the asset's history from the seller before trusting it
        yield from self.sub_flow(ResolveTransactionsFlow(
            self.seller, tx_ids=[info.asset_for_sale.ref.txhash]))
        recorded = hub.storage.get_transaction(info.asset_for_sale.ref.txhash)
        if recorded is None:
            raise FlowException("Could not resolve the offered asset")
        asset_state = recorded.tx.outputs[info.asset_for_sale.ref.index]
        if asset_state != info.asset_for_sale.state:
            raise FlowException("Offered asset does not match the chain")

        stx = yield from self.record(lambda: self._assemble(info))
        yield Send(self.seller, stx)
        # seller finalises; wait for the notarised transaction to land
        final = yield from self.wait_for_ledger_commit(stx.id)
        return final

    def _assemble(self, info: SellerTradeInfo) -> SignedTransaction:
        hub = self.service_hub
        me = hub.my_info.legal_identity
        lock_id = self.run_id or "trade"
        coins = hub.vault.try_lock_states_for_spending(
            lock_id, info.price.quantity, CashState,
            quantity_of=lambda s: s.amount.quantity,
            state_filter=lambda s: s.amount.token.product == info.price.token)
        if not coins:
            raise FlowException(f"Insufficient cash to pay {info.price}")
        # (on any failure from here the state machine releases this flow's
        # soft locks at flow end — VaultSoftLockManager semantics)
        builder = TransactionBuilder()
        # asset leg
        builder.add_input_state(info.asset_for_sale)
        move_cmd, new_asset = info.asset_for_sale.state.data.with_new_owner(
            me.owning_key)
        builder.add_output_state(new_asset, info.asset_for_sale.state.notary)
        builder.add_command(move_cmd, info.asset_for_sale.state.data.owner)
        # cash leg
        Cash.generate_spend(builder, info.price, info.seller_owner_key, coins,
                            change_owner=me.owning_key)
        builder.sign_with(hub.key_management.key_pair(me.owning_key))
        return builder.to_signed_transaction(check_sufficient_signatures=False)


def _refuse():
    raise FlowException("Malformed trade offer")
