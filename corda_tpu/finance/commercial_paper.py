"""CommercialPaper — the issue/move/redeem lifecycle contract.

Reference parity: finance/.../contracts/CommercialPaper.kt:1-236 (clause-based:
Issue checks maturity and issuer signature; Move preserves the paper and needs
the owner; Redeem needs maturity reached and the face value paid in cash to
the owner).
"""
from __future__ import annotations

from dataclasses import dataclass

from ..core.contracts.amount import Amount
from ..core.contracts.clauses import (AnyOf, Clause, GroupClauseVerifier,
                                      verify_clause)
from ..core.contracts.exceptions import TransactionVerificationException
from ..core.contracts.structures import (CommandData, Contract, OwnableState,
                                         PartyAndReference, TypeOnlyCommandData)
from ..core.crypto.keys import PublicKey
from ..core.crypto.secure_hash import SecureHash
from ..core.serialization import register_type, serializable
from .cash import CashState


@serializable("CommercialPaper.Issue")
@dataclass(frozen=True)
class Issue(TypeOnlyCommandData):
    pass


@serializable("CommercialPaper.Move")
@dataclass(frozen=True)
class Move(TypeOnlyCommandData):
    pass


@serializable("CommercialPaper.Redeem")
@dataclass(frozen=True)
class Redeem(TypeOnlyCommandData):
    pass


@serializable("CommercialPaper.State")
@dataclass(frozen=True)
class CommercialPaperState(OwnableState):
    """A promise by `issuance.party` to pay `face_value` at `maturity_micros`
    (epoch microseconds — integer time, consensus-safe) to the current owner."""

    issuance: PartyAndReference
    owner: PublicKey
    face_value: Amount            # Amount[Issued[Currency]]
    maturity_micros: int

    @property
    def contract(self) -> "CommercialPaper":
        return CP_PROGRAM

    @property
    def participants(self):
        return [self.owner]

    def with_new_owner(self, new_owner: PublicKey):
        return (Move(), CommercialPaperState(
            self.issuance, new_owner, self.face_value, self.maturity_micros))

    def without_owner(self) -> "CommercialPaperState":
        """Owner-normalized copy for move-invariance comparison."""
        return CommercialPaperState(self.issuance, _NO_KEY, self.face_value,
                                    self.maturity_micros)


_NO_KEY = None  # sentinel inside without_owner comparisons


from ..core.contracts.structures import tx_time_micros as _tx_time_micros


class IssueClause(Clause):
    required_commands = (Issue,)

    def verify(self, tx, inputs, outputs, commands, key) -> set:
        cmds = [c for c in commands if isinstance(c.value, Issue)]
        if not cmds:
            return set()
        if inputs:
            raise TransactionVerificationException(
                tx.id, "An issuance must not consume existing paper")
        if len(outputs) != 1:
            raise TransactionVerificationException(
                tx.id, "An issuance must output exactly one paper state")
        paper = outputs[0]
        if paper.face_value.quantity <= 0:
            raise TransactionVerificationException(
                tx.id, "Paper face value must be positive")
        t = _tx_time_micros(tx)
        if t is None or paper.maturity_micros <= t:
            raise TransactionVerificationException(
                tx.id, "Paper must mature in the future of the issue time-window")
        issuer_key = paper.issuance.party.owning_key
        signers = {k for c in cmds for k in c.signers}
        if not issuer_key.is_fulfilled_by(signers):
            raise TransactionVerificationException(
                tx.id, "Issue command must be signed by the issuer")
        return {c.value for c in cmds}


class MoveClause(Clause):
    required_commands = (Move,)

    def verify(self, tx, inputs, outputs, commands, key) -> set:
        cmds = [c for c in commands if isinstance(c.value, Move)]
        if not cmds:
            return set()
        if len(inputs) != 1 or len(outputs) != 1:
            raise TransactionVerificationException(
                tx.id, "A paper move consumes one paper and outputs one paper")
        if inputs[0].without_owner() != outputs[0].without_owner():
            raise TransactionVerificationException(
                tx.id, "Paper terms must not change in a move")
        signers = {k for c in cmds for k in c.signers}
        if not inputs[0].owner.is_fulfilled_by(signers):
            raise TransactionVerificationException(
                tx.id, "Move command must be signed by the paper's owner")
        return {c.value for c in cmds}


class RedeemClause(Clause):
    required_commands = (Redeem,)

    def verify(self, tx, inputs, outputs, commands, key) -> set:
        cmds = [c for c in commands if isinstance(c.value, Redeem)]
        if not cmds:
            return set()
        if len(inputs) != 1 or outputs:
            raise TransactionVerificationException(
                tx.id, "A redemption consumes the paper and outputs no paper")
        paper = inputs[0]
        t = _tx_time_micros(tx)
        if t is None or t < paper.maturity_micros:
            raise TransactionVerificationException(
                tx.id, "Paper must have matured before redemption")
        paid = sum(o.amount.quantity for o in getattr(tx, "outputs", ())
                   if isinstance(o, CashState)
                   and o.owner == paper.owner
                   and o.amount.token == paper.face_value.token)
        if paid < paper.face_value.quantity:
            raise TransactionVerificationException(
                tx.id, "Redemption must pay the face value to the owner")
        signers = {k for c in cmds for k in c.signers}
        if not paper.owner.is_fulfilled_by(signers):
            raise TransactionVerificationException(
                tx.id, "Redeem command must be signed by the paper's owner")
        return {c.value for c in cmds}


class CPGroupClause(GroupClauseVerifier):
    def __init__(self):
        super().__init__(AnyOf(IssueClause(), MoveClause(), RedeemClause()))

    def group_states(self, tx):
        return tx.group_states(CommercialPaperState,
                               lambda s: (s.issuance, s.face_value.token,
                                          s.maturity_micros))


class CommercialPaper(Contract):
    legal_contract_reference = SecureHash.sha256(
        b"corda_tpu.finance.CommercialPaper: short-term debt instrument")

    Issue = Issue
    Move = Move
    Redeem = Redeem
    State = CommercialPaperState

    def verify(self, tx) -> None:
        cp_commands = [c for c in tx.commands
                       if isinstance(c.value, (Issue, Move, Redeem))]
        verify_clause(tx, CPGroupClause(), cp_commands)

    # -- builder helpers (CommercialPaper.kt generate* methods) --------------
    @staticmethod
    def generate_issue(builder, issuance: PartyAndReference, face_value: Amount,
                       maturity_micros: int, notary) -> None:
        builder.add_output_state(
            CommercialPaperState(issuance, issuance.party.owning_key,
                                 face_value, maturity_micros), notary)
        builder.add_command(Issue(), issuance.party.owning_key)

    @staticmethod
    def generate_move(builder, paper_ref, new_owner: PublicKey) -> None:
        builder.add_input_state(paper_ref)
        builder.add_output_state(
            paper_ref.state.data.with_new_owner(new_owner)[1],
            paper_ref.state.notary)
        builder.add_command(Move(), paper_ref.state.data.owner)


CP_PROGRAM = CommercialPaper()

register_type("CommercialPaper", CommercialPaper, to_fields=lambda c: [],
              from_fields=lambda f: CP_PROGRAM)
