"""TwoPartyDealFlow — the generic two-party deal-entry protocol.

Reference parity: finance TwoPartyDealFlow.kt — Primary (the instigator)
sends a Handshake carrying the deal payload and answers the signature
request; Secondary validates the handshake, assembles the shared
transaction, signs, collects the primary's signature and finalises, then
reports the final id back. Subclass both sides and override the hooks
(``validate_handshake`` / ``assemble_shared_tx``) per deal type — the
reference's abstract Primary/Secondary split.

In this framework the primary's sign-responder half is the node-registered
SignTransactionFlow factory (sessions key by initiating flow name), so
``Primary.call`` is: send the handshake, then wait for the finalised
transaction to hit our ledger (the reference ends the same way: the
secondary sends the final tx hash back).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from ..core.serialization import register_type
from ..flows.api import (FlowException, FlowLogic, Receive, Send,
                         WaitForLedgerCommit, initiating_flow)
from ..flows.library import CollectSignaturesFlow, FinalityFlow


@dataclass(frozen=True)
class Handshake:
    """The opening message (TwoPartyDealFlow.Handshake): the deal payload
    plus the primary's identity."""

    payload: Any
    primary_identity: Any     # Party


@dataclass(frozen=True)
class DealDone:
    tx_id: Any                # SecureHash of the finalised transaction


register_type("deal.Handshake", Handshake)
register_type("deal.DealDone", DealDone)


class TwoPartyDealFlow:
    """Namespace matching the reference object."""

    @initiating_flow
    class Primary(FlowLogic):
        """The deal instigator (TwoPartyDealFlow.Primary): sends the
        handshake, lets the node's SignTransactionFlow responder answer the
        secondary's signature collection, and waits for the finalised
        transaction to land on our ledger."""

        def __init__(self, other_party, payload):
            self.other_party = other_party
            self.payload = payload

        def call(self):
            me = self.service_hub.my_info.legal_identity
            yield Send(self.other_party, Handshake(self.payload, me))
            done = yield Receive(self.other_party, DealDone)
            tx_id = done.unwrap(lambda d: d.tx_id)
            stx = yield WaitForLedgerCommit(tx_id)
            self.validate_final(stx)
            return stx

        def validate_final(self, stx) -> None:
            """Override for deal-specific checks on the finalised tx."""

    class Secondary(FlowLogic):
        """The deal acceptor (TwoPartyDealFlow.Secondary): validate the
        handshake, assemble + sign the shared transaction, collect the
        primary's signature, finalise, and report the id back. Registered
        as the responder factory for the concrete Primary subclass."""

        def __init__(self, peer):
            self.peer = peer

        def call(self):
            msg = yield Receive(self.peer, Handshake)
            handshake = msg.unwrap(self._checked)
            ptx = self.assemble_shared_tx(handshake)
            stx = yield from self.sub_flow(CollectSignaturesFlow(ptx))
            final = yield from self.sub_flow(
                FinalityFlow(stx, [handshake.primary_identity]))
            yield Send(self.peer, DealDone(final.id))
            return final

        def _checked(self, handshake: Handshake) -> Handshake:
            if str(handshake.primary_identity.name) != \
                    str(getattr(self.peer, "name", self.peer)):
                raise FlowException(
                    "Handshake identity does not match the session peer")
            self.validate_handshake(handshake)
            return handshake

        # -- hooks (abstract in the reference) ------------------------------
        def validate_handshake(self, handshake: Handshake) -> None:
            """Override: reject unacceptable proposals (raise FlowException)."""

        def assemble_shared_tx(self, handshake: Handshake):
            """Override: build + self-sign the deal transaction; return the
            partially-signed SignedTransaction."""
            raise NotImplementedError
