"""Cash — the fungible-asset contract, written in the clause framework.

Reference parity: finance/.../contracts/asset/Cash.kt:1-222 (clause-based
verify over (issuer, currency) groups) and OnLedgerAsset.kt:1-258
(generate_issue/generate_spend/generate_exit builder helpers).

Conservation rules per group (Cash.Clauses):
- Issue: no inputs consumed, positive issued amount, issuer must sign.
- Move: inputs == outputs (by amount), all input owners must sign.
- Exit: inputs == outputs + exited amount, exit keys (owners + issuer) sign.
"""
from __future__ import annotations

from dataclasses import dataclass

from ..core.contracts.amount import Amount, Currency, sum_or_zero
from ..core.contracts.clauses import (AllOf, AnyOf, Clause, FirstOf,
                                      GroupClauseVerifier, verify_clause)
from ..core.contracts.exceptions import TransactionVerificationException
from ..core.contracts.structures import (Command, CommandData, Contract,
                                         FungibleAsset, Issued,
                                         PartyAndReference,
                                         TypeOnlyCommandData, TransactionState)
from ..core.crypto.keys import PublicKey
from ..core.crypto.secure_hash import SecureHash
from ..core.serialization import serializable
from ..node.schemas import MappedSchema

#: The reference's CashSchemaV1 (finance/schemas/CashSchemaV1.kt): the
#: exportable typed projection of cash states.
CASH_SCHEMA_V1 = MappedSchema("CashSchema", 1, (
    "owner_key", "pennies", "ccy_code", "issuer_party", "issuer_ref"))


# ---------------------------------------------------------------------------
# Commands
# ---------------------------------------------------------------------------

@serializable("Cash.Issue")
@dataclass(frozen=True)
class Issue(TypeOnlyCommandData):
    pass


@serializable("Cash.Move")
@dataclass(frozen=True)
class Move(TypeOnlyCommandData):
    pass


@serializable("Cash.Exit")
@dataclass(frozen=True)
class Exit(CommandData):
    amount: Amount  # Amount[Issued[Currency]]


# ---------------------------------------------------------------------------
# State
# ---------------------------------------------------------------------------

@serializable("Cash.State")
@dataclass(frozen=True)
class CashState(FungibleAsset):
    """An amount of issued currency owned by a key (Cash.State)."""

    amount: Amount        # Amount[Issued[Currency]]
    owner: PublicKey

    @property
    def contract(self) -> "Cash":
        return CASH_PROGRAM

    @property
    def participants(self):
        return [self.owner]

    @property
    def issuer(self) -> PartyAndReference:
        return self.amount.token.issuer

    @property
    def exit_keys(self) -> set[PublicKey]:
        return {self.owner, self.amount.token.issuer.party.owning_key}

    def with_new_owner(self, new_owner: PublicKey):
        return (Move(), CashState(self.amount, new_owner))

    # -- custom schema export (finance CashSchemaV1 analog) ------------------
    def supported_schemas(self) -> tuple:
        return (CASH_SCHEMA_V1,)

    def generate_mapped_object(self, schema) -> dict:
        if schema.table_name != CASH_SCHEMA_V1.table_name:
            raise ValueError(f"unsupported schema {schema.name}")
        return {
            "owner_key": self.owner.to_string_short(),
            "pennies": self.amount.quantity,
            "ccy_code": str(self.amount.token.product),
            "issuer_party": str(self.issuer.party.name),
            "issuer_ref": self.issuer.reference.hex(),
        }


# ---------------------------------------------------------------------------
# Clauses (Cash.Clauses structure)
# ---------------------------------------------------------------------------

def _group_token(states):
    return states[0].amount.token if states else None


class IssueClause(Clause):
    """Issuance rules, parametric over the asset's command types so other
    fungible assets (finance.commodity) reuse the clause WITHOUT sharing
    command classes — shared classes would let one contract's isinstance
    filter capture the other's commands in a mixed transaction."""

    issue_command = Issue
    required_commands = (Issue,)

    def verify(self, tx, inputs, outputs, commands, token) -> set:
        issue_cmds = [c for c in commands
                      if isinstance(c.value, self.issue_command)]
        if not issue_cmds:
            return set()
        out_sum = sum_or_zero((s.amount for s in outputs), token)
        in_sum = sum_or_zero((s.amount for s in inputs), token)
        if not outputs:
            raise TransactionVerificationException(
                tx.id, "Issue transaction must output cash")
        if out_sum.quantity <= in_sum.quantity:
            raise TransactionVerificationException(
                tx.id, "Issued amount must be positive")
        issuer_key = token.issuer.party.owning_key
        for cmd in issue_cmds:
            # fulfil against the signer SET (a composite issuer key needs its
            # threshold met across several leaf signatures)
            if not issuer_key.is_fulfilled_by(set(cmd.signers)):
                raise TransactionVerificationException(
                    tx.id, "Issue command must be signed by the issuer")
        return {c.value for c in issue_cmds}


class MoveClause(Clause):
    move_command = Move
    exit_command = Exit
    required_commands = (Move,)

    def verify(self, tx, inputs, outputs, commands, token) -> set:
        move_cmds = [c for c in commands
                     if isinstance(c.value, self.move_command)]
        if not move_cmds:
            return set()
        in_sum = sum_or_zero((s.amount for s in inputs), token)
        out_sum = sum_or_zero((s.amount for s in outputs), token)
        exit_amount = sum((c.value.amount.quantity for c in commands
                           if isinstance(c.value, self.exit_command)
                           and c.value.amount.token == token), 0)
        if in_sum.quantity != out_sum.quantity + exit_amount:
            raise TransactionVerificationException(
                tx.id, f"Cash not conserved for {token}: "
                       f"{in_sum.quantity} in vs {out_sum.quantity} out")
        owner_keys = {s.owner for s in inputs}
        signers = {k for c in move_cmds for k in c.signers}
        for key in owner_keys:
            if not key.is_fulfilled_by(signers):
                raise TransactionVerificationException(
                    tx.id, "Move command must be signed by every input owner")
        return {c.value for c in move_cmds}


class ExitClause(Clause):
    exit_command = Exit
    required_commands = (Exit,)

    def verify(self, tx, inputs, outputs, commands, token) -> set:
        exit_cmds = [c for c in commands
                     if isinstance(c.value, self.exit_command)
                     and c.value.amount.token == token]
        if not exit_cmds:
            return set()
        # Conservation must hold on the exit path too (the reference's
        # ConserveAmount applies to every non-issue group): an Exit-only
        # transaction may not create or destroy more value than it declares.
        in_sum = sum_or_zero((s.amount for s in inputs), token)
        out_sum = sum_or_zero((s.amount for s in outputs), token)
        exit_amount = sum(c.value.amount.quantity for c in exit_cmds)
        if in_sum.quantity != out_sum.quantity + exit_amount:
            raise TransactionVerificationException(
                tx.id, f"Cash not conserved on exit for {token}: {in_sum.quantity} "
                       f"in vs {out_sum.quantity} out + {exit_amount} exited")
        required = {k for s in inputs for k in s.exit_keys}
        signers = {k for c in exit_cmds for k in c.signers}
        for key in required:
            if not key.is_fulfilled_by(signers):
                raise TransactionVerificationException(
                    tx.id, "Exit command requires owner and issuer signatures")
        return {c.value for c in exit_cmds}


class CashGroupClause(GroupClauseVerifier):
    def __init__(self):
        super().__init__(AnyOf(IssueClause(), MoveClause(), ExitClause()))

    def group_states(self, tx):
        return tx.group_states(CashState, lambda s: s.amount.token)


class Cash(Contract):
    """The cash contract object (Cash.kt)."""

    legal_contract_reference = SecureHash.sha256(
        b"corda_tpu.finance.Cash: fungible currency claims")

    Issue = Issue
    Move = Move
    Exit = Exit
    State = CashState

    def verify(self, tx) -> None:
        cash_commands = [c for c in tx.commands
                         if isinstance(c.value, (Issue, Move, Exit))]
        verify_clause(tx, CashGroupClause(), cash_commands)

    # -- builder helpers (OnLedgerAsset.kt) ----------------------------------
    @staticmethod
    def generate_issue(builder, amount: Amount, issuer: PartyAndReference,
                       owner: PublicKey, notary) -> None:
        """amount: Amount[Currency]; wraps into Amount[Issued[Currency]]."""
        issued = Amount(amount.quantity, Issued(issuer, amount.token))
        builder.add_output_state(CashState(issued, owner), notary)
        builder.add_command(Issue(), issuer.party.owning_key)

    @staticmethod
    def generate_spend(builder, amount: Amount, to: PublicKey,
                       coins: list, change_owner: PublicKey) -> list[PublicKey]:
        """Add inputs/outputs moving `amount` (Amount[Currency]) from `coins`
        (StateAndRefs) to `to`, with change back to `change_owner`. Returns the
        keys that must sign.

        Coins must all be in `amount`'s currency (callers filter at selection)
        but may span issuers: conservation holds per (issuer, currency) token
        group, so the payment is emitted as one output per issuer token drawn
        on, with per-token change (OnLedgerAsset.kt's grouped spend)."""
        used, gathered = [], 0
        for sar in coins:
            if sar.state.data.amount.token.product != amount.token:
                raise ValueError(
                    f"Coin in {sar.state.data.amount.token.product}, "
                    f"spend is in {amount.token}")
            used.append(sar)
            gathered += sar.state.data.amount.quantity
            if gathered >= amount.quantity:
                break
        if gathered < amount.quantity:
            raise InsufficientBalanceException(amount.quantity - gathered)
        notary = used[0].state.notary
        by_token: dict = {}
        for sar in used:
            builder.add_input_state(sar)
            token = sar.state.data.amount.token
            by_token[token] = by_token.get(token, 0) + sar.state.data.amount.quantity
        need = amount.quantity
        for token, total in by_token.items():
            pay = min(need, total)
            need -= pay
            if pay:
                builder.add_output_state(CashState(Amount(pay, token), to),
                                         notary)
            if total > pay:
                builder.add_output_state(
                    CashState(Amount(total - pay, token), change_owner), notary)
        keys = sorted({sar.state.data.owner for sar in used})
        builder.add_command(Move(), *keys)
        return keys


class InsufficientBalanceException(Exception):
    def __init__(self, shortfall):
        super().__init__(f"Insufficient balance, short by {shortfall}")
        self.shortfall = shortfall


CASH_PROGRAM = Cash()

from ..core.serialization import register_type as _register_type  # noqa: E402

_register_type("Cash", Cash, to_fields=lambda c: [],
               from_fields=lambda f: CASH_PROGRAM)
