"""Commodity claims — fungible assets whose product is a commodity, not a
currency.

Reference parity: finance CommodityContract.kt (the "cut-n-paste of Cash"
the reference itself documents — an OnLedgerAsset over Commodity products).
The TPU-native build DE-duplicates instead: the Issue/Move/Exit group
clauses are generic over FungibleAsset amounts (finance.cash), so this
module adds only the Commodity product type, the state, and the contract
shell reusing them.
"""
from __future__ import annotations

from dataclasses import dataclass

from ..core.contracts.amount import Amount
from ..core.contracts.clauses import AnyOf, GroupClauseVerifier, verify_clause
from ..core.contracts.structures import (CommandData, FungibleAsset, Issued,
                                         PartyAndReference,
                                         TypeOnlyCommandData)
from ..core.crypto.keys import PublicKey
from ..core.crypto.secure_hash import SecureHash
from ..core.serialization import register_type, serializable
from .cash import Contract, ExitClause, IssueClause, MoveClause


@serializable("finance.Commodity")
@dataclass(frozen=True)
class Commodity:
    """A tradeable commodity (reference Commodity data class): identified by
    its commodity code, e.g. "FCOJ" — frozen concentrated orange juice."""

    commodity_code: str
    display_name: str = ""
    default_fraction_digits: int = 0

    def __str__(self):
        return self.commodity_code


# INDEPENDENT command types (not subclasses of Cash's): in a mixed
# cash+commodity transaction each contract's isinstance filter must see
# ONLY its own commands — a shared hierarchy would apply cash conservation
# to commodity commands and vice versa (review r3).

@serializable("Commodity.Issue")
@dataclass(frozen=True)
class Issue(TypeOnlyCommandData):
    """Issue commodity claims (CommodityContract.Commands.Issue)."""


@serializable("Commodity.Move")
@dataclass(frozen=True)
class Move(TypeOnlyCommandData):
    """Move commodity claims (CommodityContract.Commands.Move)."""


@serializable("Commodity.Exit")
@dataclass(frozen=True)
class Exit(CommandData):
    """Exit commodity claims (CommodityContract.Commands.Exit)."""

    amount: Amount  # Amount[Issued[Commodity]]


@serializable("finance.CommodityState")
@dataclass(frozen=True)
class CommodityState(FungibleAsset):
    """An amount of an issued commodity owned by a key
    (CommodityContract.State)."""

    amount: Amount        # Amount[Issued[Commodity]]
    owner: PublicKey

    @property
    def contract(self) -> "CommodityContract":
        return COMMODITY_PROGRAM

    @property
    def participants(self):
        return [self.owner]

    @property
    def issuer(self) -> PartyAndReference:
        return self.amount.token.issuer

    @property
    def exit_keys(self) -> set[PublicKey]:
        return {self.owner, self.amount.token.issuer.party.owning_key}

    def with_new_owner(self, new_owner: PublicKey):
        return (Move(), CommodityState(self.amount, new_owner))


class CommodityIssueClause(IssueClause):
    issue_command = Issue
    required_commands = (Issue,)


class CommodityMoveClause(MoveClause):
    move_command = Move
    exit_command = Exit
    required_commands = (Move,)


class CommodityExitClause(ExitClause):
    exit_command = Exit
    required_commands = (Exit,)


class CommodityGroupClause(GroupClauseVerifier):
    def __init__(self):
        super().__init__(AnyOf(CommodityIssueClause(), CommodityMoveClause(),
                               CommodityExitClause()))

    def group_states(self, tx):
        return tx.group_states(CommodityState, lambda s: s.amount.token)


class CommodityContract(Contract):
    """The commodity contract (CommodityContract.kt), sharing the cash
    clauses — conservation per (issuer, commodity) token group, issuer-signed
    issuance, owner-signed moves, owner+issuer-signed exits."""

    legal_contract_reference = SecureHash.sha256(
        b"corda_tpu.finance.CommodityContract: commodity claims")

    Issue = Issue
    Move = Move
    Exit = Exit
    State = CommodityState

    def verify(self, tx) -> None:
        commands = [c for c in tx.commands
                    if isinstance(c.value, (Issue, Move, Exit))]
        verify_clause(tx, CommodityGroupClause(), commands)

    @staticmethod
    def generate_issue(builder, amount: Amount, issuer: PartyAndReference,
                       owner: PublicKey, notary) -> None:
        """amount: Amount[Commodity] → Amount[Issued[Commodity]] output."""
        issued = Amount(amount.quantity, Issued(issuer, amount.token))
        builder.add_output_state(CommodityState(issued, owner), notary)
        builder.add_command(Issue(), issuer.party.owning_key)

    @staticmethod
    def generate_move(builder, sar, new_owner: PublicKey) -> PublicKey:
        """Move one whole holding to ``new_owner``; returns the key that
        must sign."""
        builder.add_input_state(sar)
        builder.add_output_state(
            CommodityState(sar.state.data.amount, new_owner),
            sar.state.notary)
        builder.add_command(Move(), sar.state.data.owner)
        return sar.state.data.owner


COMMODITY_PROGRAM = CommodityContract()
