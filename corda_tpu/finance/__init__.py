"""Finance: reference contracts + flows (the `finance/` module of the
reference — Cash, CommercialPaper, Obligation and the cash flows)."""
from .cash import Cash, CashState  # noqa: F401
from .commercial_paper import CommercialPaper, CommercialPaperState  # noqa: F401
from .commodity import Commodity, CommodityContract, CommodityState  # noqa: F401
from .deal import TwoPartyDealFlow  # noqa: F401
from .flows import CashIssueFlow, CashPaymentFlow, CashExitFlow  # noqa: F401
from .obligation import Obligation, ObligationState  # noqa: F401
from .trade import BuyerFlow, SellerFlow  # noqa: F401
