"""ProgressTracker — hierarchical flow progress with a change stream.

Reference parity: core/utilities/ProgressTracker.kt:37-125 — a flow declares
ordered `Step`s, may attach a child tracker to a step, and observers receive
(tracker, change) events as the current step moves; the RPC layer streams
these to clients (stateMachinesAndUpdates) and the shell renders them
(ANSIProgressRenderer).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Callable


@dataclass(frozen=True)
class Step:
    label: str


UNSTARTED = Step("Unstarted")
DONE = Step("Done")


class ProgressTracker:
    def __init__(self, *steps: Step):
        self.steps = (UNSTARTED, *steps, DONE)
        self._index = 0
        self._children: dict[Step, "ProgressTracker"] = {}
        self._observers: list[Callable] = []
        self.parent: "ProgressTracker | None" = None

    # -- state ---------------------------------------------------------------
    @property
    def current_step(self) -> Step:
        return self.steps[self._index]

    @current_step.setter
    def current_step(self, step: Step) -> None:
        if step not in self.steps:
            raise ValueError(f"{step} is not a step of this tracker")
        self._index = self.steps.index(step)
        self._emit(("position", self, step))

    def next_step(self) -> Step:
        if self._index < len(self.steps) - 1:
            self._index += 1
            self._emit(("position", self, self.current_step))
        return self.current_step

    @property
    def has_ended(self) -> bool:
        return self.current_step == DONE

    # -- hierarchy -----------------------------------------------------------
    def set_child_progress_tracker(self, step: Step,
                                   child: "ProgressTracker") -> None:
        self._children[step] = child
        child.parent = self
        child._observers.append(self._emit)

    def get_child_progress_tracker(self, step: Step):
        return self._children.get(step)

    # -- observation ---------------------------------------------------------
    def subscribe(self, observer: Callable) -> None:
        self._observers.append(observer)

    def _emit(self, change) -> None:
        for obs in list(self._observers):
            obs(change)

    # -- rendering (the shell's ANSIProgressRenderer line format) ------------
    def render(self, indent: int = 0) -> str:
        lines = []
        for i, step in enumerate(self.steps[1:-1], start=1):
            marker = ("✓" if i < self._index
                      else "▶" if i == self._index else " ")
            lines.append("  " * indent + f"{marker} {step.label}")
            child = self._children.get(step)
            if child is not None:
                lines.append(child.render(indent + 1))
        return "\n".join(lines)
