"""Seeded, deterministic fault injection — the chaos harness.

Jepsen-style testing needs faults that are (a) injectable at precise
points in the stack and (b) exactly reproducible from a seed. This module
provides both: production code calls ``fault_point("tcp.send", detail=...)``
at its failure-prone seams, and tests arm a :class:`FaultInjector` with
:class:`FaultRule` schedules describing *which* hits fire and *what*
happens (drop / delay / raise / duplicate).

Design constraints:

* **Zero cost disarmed.** ``fault_point`` is a module-level function whose
  first statement checks a module-level bool. With no injector armed the
  call is one global load + one branch — nothing allocates, no lock is
  taken. Production hot paths (the batcher dispatch loop, the TCP sender)
  keep their benchmarked profile.
* **Deterministic.** Every probabilistic rule draws from its own
  ``random.Random`` seeded from ``(injector seed, rule index)``; count
  predicates (``after`` / ``count`` / ``every``) are plain counters. The
  same seed + the same sequence of fault-point hits ⇒ the same faults.
  The seed defaults to ``CORDA_TPU_FAULT_SEED`` from the environment so a
  red chaos run is reproducible verbatim from its log line.
* **Composable actions.** ``raise`` and ``delay`` are handled inside
  ``fault_point`` (every call site gets them for free); ``drop`` and
  ``duplicate`` are *returned* to the call site, because only the call
  site knows what skipping or doubling its operation means. Sites that
  cannot duplicate simply ignore the return value.

Fault-point catalog (see docs/ROBUSTNESS.md):

====================== ======================================================
point                  seam
====================== ======================================================
``tcp.send``           TCP plane, before a frame is written to the socket
``tcp.connect``        TCP plane, before dialing a peer
``net.send``           in-memory bus, before a message is enqueued
``raft.append``        raft, before posting an AppendEntries (python + native)
``batcher.device_dispatch`` SignatureBatcher, inside the device-dispatch try
``oop.deliver``        verifier queue → worker request send
``oop.reply``          verifier worker → service reply send
``kvstore.flush``      KvStore, before the engine append (durability seam)
``smm.checkpoint_remove`` SMM ``_finalize``, before ``remove_checkpoint``
``raft.snapshot.persist`` RaftLogStore.save_snapshot, between the snapshot
                       write and the covered-prefix delete (torn-persist seam)
``raft.snapshot.install`` raft leader, before posting an InstallSnapshot
``coordlog.compact``   CoordinatorLog GC, after the side-file fsync and
                       before the atomic rename over the live log
====================== ======================================================

``detail`` carries the call-site specifics (``"alice->bob"`` on sends,
the scheme name on batcher dispatch) and rules may target it with an
fnmatch pattern — that is how a test partitions one raft node or storms
one signature scheme.
"""
from __future__ import annotations

import fnmatch
import logging
import os
import random
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field

from ..observability.slog import jlog

_log = logging.getLogger("corda_tpu.faults")

#: sentinel return values of :func:`fault_point` — call sites compare with
#: ``==`` (they are plain strings so tests can assert on fire logs too)
DROP = "drop"
DUPLICATE = "duplicate"


class FaultError(ConnectionError):
    """Default exception for ``action="raise"`` rules.

    Subclasses :class:`ConnectionError` (hence :class:`OSError`) on
    purpose: transport retry paths catch ``(OSError, ConnectionError,
    LookupError)``, so an injected fault exercises exactly the handler a
    real socket failure would."""


@dataclass
class FaultRule:
    """One scheduled fault. ``point`` (and optionally ``detail``) are
    fnmatch patterns; the count predicates select which eligible hits
    actually fire: skip the first ``after``, then fire every ``every``-th
    with ``probability``, at most ``count`` times total."""
    point: str
    action: str = "raise"          # drop | delay | raise | duplicate
    detail: str | None = None      # fnmatch over the call-site detail
    after: int = 0                 # skip the first N eligible hits
    count: int | None = None       # fire at most N times (None = unlimited)
    every: int = 1                 # of the eligible hits, fire each k-th
    probability: float = 1.0       # seeded coin flip per eligible hit
    delay_s: float = 0.0           # for action="delay"
    exc: Exception | type | None = None   # for action="raise"
    matches: int = field(default=0, repr=False)   # eligible hits seen
    fires: int = field(default=0, repr=False)     # times actually fired

    def _make_exc(self, name: str, detail: str | None) -> Exception:
        if self.exc is None:
            return FaultError(f"injected fault at {name}"
                              + (f" ({detail})" if detail else ""))
        if isinstance(self.exc, type):
            return self.exc(f"injected fault at {name}")
        return self.exc


class FaultInjector:
    """Process-wide fault schedule. Arm with :func:`arm` / :func:`inject`;
    every armed hit is recorded in ``self.log`` as ``(point, detail,
    action)`` so tests can assert on exactly what fired."""

    def __init__(self, seed: int | None = None):
        if seed is None:
            seed = int(os.environ.get("CORDA_TPU_FAULT_SEED", "0") or 0)
        self.seed = seed
        self.rules: list[FaultRule] = []
        self.log: list[tuple[str, str | None, str]] = []
        self._rngs: list[random.Random] = []
        self._lock = threading.Lock()

    def add(self, rule: FaultRule) -> FaultRule:
        with self._lock:
            self.rules.append(rule)
            # one rng per rule: rules fire deterministically regardless of
            # what other (possibly probabilistic) rules are armed alongside
            self._rngs.append(random.Random(self.seed * 1_000_003
                                            + len(self.rules)))
        return rule

    def fired(self, point: str) -> int:
        """How many times any rule fired at fault points matching *point*."""
        return sum(1 for p, _, _ in self.log if fnmatch.fnmatch(p, point))

    # -- the hit path (only reached while armed) ----------------------------
    def _hit(self, name: str, detail: str | None) -> str | None:
        outcome = None
        with self._lock:
            for rule, rng in zip(self.rules, self._rngs):
                if not fnmatch.fnmatch(name, rule.point):
                    continue
                if rule.detail is not None and (
                        detail is None
                        or not fnmatch.fnmatch(detail, rule.detail)):
                    continue
                rule.matches += 1
                if rule.matches <= rule.after:
                    continue
                if rule.count is not None and rule.fires >= rule.count:
                    continue
                if (rule.matches - rule.after - 1) % rule.every:
                    continue
                if rule.probability < 1.0 and \
                        rng.random() >= rule.probability:
                    continue
                rule.fires += 1
                self.log.append((name, detail, rule.action))
                jlog(_log, "fault.fire", point=name, detail=detail,
                     action=rule.action, seed=self.seed, fire=rule.fires)
                if rule.action == "delay":
                    # sleep outside the lock; keep scanning afterwards so a
                    # delay rule can compose with a drop/raise rule
                    delay = rule.delay_s
                    self._lock.release()
                    try:
                        time.sleep(delay)
                    finally:
                        self._lock.acquire()
                    continue
                if rule.action == "raise":
                    raise rule._make_exc(name, detail)
                outcome = rule.action          # drop | duplicate
                break
        return outcome


# -- process-wide arming ----------------------------------------------------
_ARMED = False            # the fast-path gate: read unlocked, set rarely
_INJECTOR: FaultInjector | None = None


def fault_point(name: str, detail: str | None = None) -> str | None:
    """Call-site hook. Returns ``None`` (armed or not) unless a drop or
    duplicate rule fires, in which case the sentinel string is returned
    for the call site to act on. Raise/delay rules act in here."""
    if not _ARMED:                 # the zero-cost disarmed path
        return None
    inj = _INJECTOR
    if inj is None:
        return None
    return inj._hit(name, detail)


def arm(injector: FaultInjector) -> FaultInjector:
    global _ARMED, _INJECTOR
    _INJECTOR = injector
    _ARMED = True
    jlog(_log, "fault.arm", seed=injector.seed,
         rules=[r.point for r in injector.rules])
    return injector


def disarm() -> None:
    global _ARMED, _INJECTOR
    _ARMED = False
    _INJECTOR = None


def active() -> FaultInjector | None:
    """The armed injector, if any — the conftest failure hook reads its
    seed so every red chaos run prints its reproduction recipe."""
    return _INJECTOR if _ARMED else None


@contextmanager
def inject(*rules: FaultRule, seed: int | None = None):
    """``with inject(FaultRule("tcp.send", "drop", count=3), seed=7) as inj:``
    — arm for the block, always disarm after (even on assertion failure),
    yield the injector for fire-log assertions."""
    inj = FaultInjector(seed=seed)
    for rule in rules:
        inj.add(rule)
    arm(inj)
    try:
        yield inj
    finally:
        disarm()
