"""Minimal metrics registry (codahale-style: meters, timers, gauges, counters).

Reference parity: MonitoringService (services/api/MonitoringService.kt:11) and
the named verification metrics of OutOfProcessTransactionVerifierService.kt:33-45
("Verification.Duration/Success/Failure/InFlight"). Thread-safe; snapshot-able
for export (the JMX analog is `snapshot()` → dict, consumable by any exporter).
"""
from __future__ import annotations

import bisect
import math
import threading
import time
from collections import defaultdict


class Meter:
    """Monotone event counter with a rate since creation."""

    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self._t0 = time.monotonic()

    def mark(self, n: int = 1) -> None:
        with self._lock:
            self.count += n

    def mean_rate(self) -> float:
        dt = time.monotonic() - self._t0
        return self.count / dt if dt > 0 else 0.0


class Timer:
    """Duration accumulator; use as a context manager."""

    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0

    def __enter__(self):
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.update(time.perf_counter() - self._start)
        return False

    def update(self, seconds: float) -> None:
        with self._lock:
            self.count += 1
            self.total_s += seconds
            self.max_s = max(self.max_s, seconds)

    def mean_s(self) -> float:
        return self.total_s / self.count if self.count else 0.0


#: Fixed log-scaled bucket upper bounds: quarter-decade steps (×~1.78)
#: from 1e-6 to 1e7 — one layout covers microsecond latencies AND 32k-item
#: batch sizes, so every histogram snapshot/exposition has identical shape
#: and two registries' histograms are directly comparable.
_HIST_BOUNDS = tuple(10.0 ** (e / 4.0) for e in range(-24, 29))


class Histogram:
    """Fixed-bucket log-scaled histogram with quantile snapshots.

    Quantiles are bucket-resolution estimates (within ×10^0.25 ≈ 1.78 of
    the true value), clamped to the observed max — the standard
    fixed-bucket trade: O(1) update, O(buckets) snapshot, no per-sample
    storage, mergeable across processes by summing counts.

    Observations may carry a ``trace_id`` (the tracer's, observability/
    tracing.py): the histogram keeps the LAST exemplar per bucket, so a
    slow p99 bucket in /metrics links directly to a concrete span in the
    trace ring — the Dapper "exemplar" pattern, one dict write per traced
    observation, nothing stored for untraced ones."""

    BOUNDS = _HIST_BOUNDS

    def __init__(self):
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.BOUNDS) + 1)  # +1 overflow bucket
        self.count = 0
        self.total = 0.0
        self.max_value = 0.0
        # bucket index -> (trace_id, value, unix ts): last exemplar only
        self._exemplars: dict[int, tuple] = {}

    def update(self, value: float, trace_id: str | None = None) -> None:
        v = float(value)
        idx = bisect.bisect_left(self.BOUNDS, v)
        with self._lock:
            self._counts[idx] += 1
            self.count += 1
            self.total += v
            if v > self.max_value:
                self.max_value = v
            if trace_id is not None:
                self._exemplars[idx] = (trace_id, v, time.time())

    def _bucket_le(self, idx: int) -> str:
        return (f"{self.BOUNDS[idx]:.6g}" if idx < len(self.BOUNDS)
                else "+Inf")

    def exemplars(self) -> dict:
        """Last exemplar per bucket: {le: {trace_id, value, ts}} — the
        /metrics JSON + Prometheus exposition surface."""
        with self._lock:
            items = list(self._exemplars.items())
        return {self._bucket_le(i): {"trace_id": t, "value": v, "ts": ts}
                for i, (t, v, ts) in sorted(items)}

    def bucket_counts(self) -> list:
        """Cumulative (le, count) pairs for non-empty buckets plus +Inf —
        the Prometheus histogram exposition shape."""
        with self._lock:
            counts = list(self._counts)
            total = self.count
        out = []
        cum = 0
        for i, c in enumerate(counts[:-1]):
            cum += c
            if c:
                out.append((self._bucket_le(i), cum))
        out.append(("+Inf", total))
        return out

    def __enter__(self):
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.update(time.perf_counter() - self._start)
        return False

    def quantile(self, q: float) -> float:
        """q-quantile estimate, linearly interpolated WITHIN the bucket
        holding the target sample (0 when empty), clamped to the observed
        maximum. Snapping to the bucket's upper edge — the previous
        behaviour — overstates tails by up to one quarter-decade (×1.78)
        whenever the target rank lands early in a log bucket; the rank
        fraction positions the estimate between the bucket's edges
        instead."""
        with self._lock:
            counts = list(self._counts)
            count = self.count
            max_v = self.max_value
        if count == 0:
            return 0.0
        target = max(1, math.ceil(q * count))
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            if cum >= target:
                if i >= len(self.BOUNDS):
                    return max_v     # overflow bucket: max is all we know
                lo = self.BOUNDS[i - 1] if i > 0 else 0.0
                hi = self.BOUNDS[i]
                frac = (target - (cum - c)) / c
                return min(lo + frac * (hi - lo), max_v)
        return max_v

    def snapshot_fields(self) -> dict:
        with self._lock:
            count, total, max_v = self.count, self.total, self.max_value
            has_exemplars = bool(self._exemplars)
        out = {"count": count, "sum": total, "max": max_v,
               "mean": total / count if count else 0.0,
               "p50": self.quantile(0.50), "p90": self.quantile(0.90),
               "p99": self.quantile(0.99),
               "buckets": self.bucket_counts()}
        if has_exemplars:
            out["exemplars"] = self.exemplars()
        return out


class Counter:
    """Up/down counter (the in-flight gauge analog)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.value += n

    def dec(self, n: int = 1) -> None:
        self.inc(-n)


class Gauge:
    """Settable instantaneous level with a high-water mark.

    Counter tracks net increments; Gauge records observed *levels* and the
    maximum ever seen — the shape of the batcher's prep-pool concurrency
    metric, where the high-water mark (how many scheme preps actually
    overlapped) is the interesting number and the instantaneous value is
    usually zero by the time anyone snapshots."""

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0.0
        self.max_value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self.value = value
            if value > self.max_value:
                self.max_value = value


class MetricRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}
        self._collectors: list = []

    def _get(self, name: str, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls()
            elif not isinstance(m, cls):
                raise TypeError(f"metric {name!r} already registered as {type(m).__name__}")
            return m

    def meter(self, name: str) -> Meter:
        return self._get(name, Meter)

    def timer(self, name: str) -> Timer:
        return self._get(name, Timer)

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def settable_gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def gauge(self, name: str, fn) -> None:
        with self._lock:
            self._metrics[name] = fn

    def register(self, name: str, metric) -> None:
        """Install an EXISTING metric object under ``name`` — the seam the
        kernel profiler uses to share its process-wide histograms with
        every registry that exports them (node monitoring + bench's
        private registry see the same distribution)."""
        with self._lock:
            self._metrics[name] = metric

    def get_metric(self, name: str):
        with self._lock:
            return self._metrics.get(name)

    def add_collector(self, fn) -> None:
        """Register a zero-arg callable returning ``{name: fields}`` whose
        entries ride every ``snapshot()`` — the seam federated (per-worker
        labeled) families use to appear on /metrics without being local
        metric objects. Locally-registered metrics win on name collision;
        a raising collector is skipped, never kills the snapshot."""
        with self._lock:
            self._collectors.append(fn)

    def snapshot(self) -> dict:
        """Registry → {name: fields} with a ``type`` discriminator per
        metric, so exporters (prometheus_text) can render each family
        correctly instead of guessing from field names."""
        out = {}
        with self._lock:
            items = list(self._metrics.items())
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                extra = fn()
            except Exception:
                continue
            if isinstance(extra, dict):
                out.update(extra)
        for name, m in items:
            if isinstance(m, Meter):
                out[name] = {"type": "meter", "count": m.count,
                             "mean_rate": m.mean_rate()}
            elif isinstance(m, Timer):
                out[name] = {"type": "timer", "count": m.count,
                             "mean_s": m.mean_s(), "max_s": m.max_s}
            elif isinstance(m, Counter):
                out[name] = {"type": "counter", "value": m.value}
            elif isinstance(m, Histogram):
                out[name] = {"type": "histogram", **m.snapshot_fields()}
            elif isinstance(m, Gauge):
                out[name] = {"type": "gauge", "value": m.value,
                             "max": m.max_value}
            else:
                try:
                    value = m()
                except Exception:   # a dead gauge fn must not kill /metrics
                    value = None
                out[name] = {"type": "gauge_fn", "value": value}
        return out
