"""Minimal metrics registry (codahale-style: meters, timers, gauges, counters).

Reference parity: MonitoringService (services/api/MonitoringService.kt:11) and
the named verification metrics of OutOfProcessTransactionVerifierService.kt:33-45
("Verification.Duration/Success/Failure/InFlight"). Thread-safe; snapshot-able
for export (the JMX analog is `snapshot()` → dict, consumable by any exporter).
"""
from __future__ import annotations

import threading
import time
from collections import defaultdict


class Meter:
    """Monotone event counter with a rate since creation."""

    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self._t0 = time.monotonic()

    def mark(self, n: int = 1) -> None:
        with self._lock:
            self.count += n

    def mean_rate(self) -> float:
        dt = time.monotonic() - self._t0
        return self.count / dt if dt > 0 else 0.0


class Timer:
    """Duration accumulator; use as a context manager."""

    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0

    def __enter__(self):
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.update(time.perf_counter() - self._start)
        return False

    def update(self, seconds: float) -> None:
        with self._lock:
            self.count += 1
            self.total_s += seconds
            self.max_s = max(self.max_s, seconds)

    def mean_s(self) -> float:
        return self.total_s / self.count if self.count else 0.0


class Counter:
    """Up/down counter (the in-flight gauge analog)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.value += n

    def dec(self, n: int = 1) -> None:
        self.inc(-n)


class MetricRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}

    def _get(self, name: str, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls()
            elif not isinstance(m, cls):
                raise TypeError(f"metric {name!r} already registered as {type(m).__name__}")
            return m

    def meter(self, name: str) -> Meter:
        return self._get(name, Meter)

    def timer(self, name: str) -> Timer:
        return self._get(name, Timer)

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str, fn) -> None:
        with self._lock:
            self._metrics[name] = fn

    def snapshot(self) -> dict:
        out = {}
        with self._lock:
            items = list(self._metrics.items())
        for name, m in items:
            if isinstance(m, Meter):
                out[name] = {"count": m.count, "mean_rate": m.mean_rate()}
            elif isinstance(m, Timer):
                out[name] = {"count": m.count, "mean_s": m.mean_s(), "max_s": m.max_s}
            elif isinstance(m, Counter):
                out[name] = {"value": m.value}
            else:
                out[name] = {"value": m()}
        return out
