"""Minimal metrics registry (codahale-style: meters, timers, gauges, counters).

Reference parity: MonitoringService (services/api/MonitoringService.kt:11) and
the named verification metrics of OutOfProcessTransactionVerifierService.kt:33-45
("Verification.Duration/Success/Failure/InFlight"). Thread-safe; snapshot-able
for export (the JMX analog is `snapshot()` → dict, consumable by any exporter).
"""
from __future__ import annotations

import bisect
import math
import threading
import time
from collections import defaultdict


class Meter:
    """Monotone event counter with a rate since creation."""

    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self._t0 = time.monotonic()

    def mark(self, n: int = 1) -> None:
        with self._lock:
            self.count += n

    def mean_rate(self) -> float:
        dt = time.monotonic() - self._t0
        return self.count / dt if dt > 0 else 0.0


class Timer:
    """Duration accumulator; use as a context manager."""

    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0

    def __enter__(self):
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.update(time.perf_counter() - self._start)
        return False

    def update(self, seconds: float) -> None:
        with self._lock:
            self.count += 1
            self.total_s += seconds
            self.max_s = max(self.max_s, seconds)

    def mean_s(self) -> float:
        return self.total_s / self.count if self.count else 0.0


#: Fixed log-scaled bucket upper bounds: quarter-decade steps (×~1.78)
#: from 1e-6 to 1e7 — one layout covers microsecond latencies AND 32k-item
#: batch sizes, so every histogram snapshot/exposition has identical shape
#: and two registries' histograms are directly comparable.
_HIST_BOUNDS = tuple(10.0 ** (e / 4.0) for e in range(-24, 29))


class Histogram:
    """Fixed-bucket log-scaled histogram with quantile snapshots.

    Quantiles are bucket-resolution estimates (within ×10^0.25 ≈ 1.78 of
    the true value), clamped to the observed max — the standard
    fixed-bucket trade: O(1) update, O(buckets) snapshot, no per-sample
    storage, mergeable across processes by summing counts."""

    BOUNDS = _HIST_BOUNDS

    def __init__(self):
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.BOUNDS) + 1)  # +1 overflow bucket
        self.count = 0
        self.total = 0.0
        self.max_value = 0.0

    def update(self, value: float) -> None:
        v = float(value)
        idx = bisect.bisect_left(self.BOUNDS, v)
        with self._lock:
            self._counts[idx] += 1
            self.count += 1
            self.total += v
            if v > self.max_value:
                self.max_value = v

    def __enter__(self):
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.update(time.perf_counter() - self._start)
        return False

    def quantile(self, q: float) -> float:
        """Upper bound of the bucket holding the q-quantile sample (0 when
        empty), clamped to the observed maximum."""
        with self._lock:
            counts = list(self._counts)
            count = self.count
            max_v = self.max_value
        if count == 0:
            return 0.0
        target = max(1, math.ceil(q * count))
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            if cum >= target:
                if i < len(self.BOUNDS):
                    return min(self.BOUNDS[i], max_v)
                return max_v
        return max_v

    def snapshot_fields(self) -> dict:
        with self._lock:
            count, total, max_v = self.count, self.total, self.max_value
        return {"count": count, "sum": total, "max": max_v,
                "mean": total / count if count else 0.0,
                "p50": self.quantile(0.50), "p90": self.quantile(0.90),
                "p99": self.quantile(0.99)}


class Counter:
    """Up/down counter (the in-flight gauge analog)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.value += n

    def dec(self, n: int = 1) -> None:
        self.inc(-n)


class Gauge:
    """Settable instantaneous level with a high-water mark.

    Counter tracks net increments; Gauge records observed *levels* and the
    maximum ever seen — the shape of the batcher's prep-pool concurrency
    metric, where the high-water mark (how many scheme preps actually
    overlapped) is the interesting number and the instantaneous value is
    usually zero by the time anyone snapshots."""

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0.0
        self.max_value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self.value = value
            if value > self.max_value:
                self.max_value = value


class MetricRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}

    def _get(self, name: str, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls()
            elif not isinstance(m, cls):
                raise TypeError(f"metric {name!r} already registered as {type(m).__name__}")
            return m

    def meter(self, name: str) -> Meter:
        return self._get(name, Meter)

    def timer(self, name: str) -> Timer:
        return self._get(name, Timer)

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def settable_gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def gauge(self, name: str, fn) -> None:
        with self._lock:
            self._metrics[name] = fn

    def snapshot(self) -> dict:
        out = {}
        with self._lock:
            items = list(self._metrics.items())
        for name, m in items:
            if isinstance(m, Meter):
                out[name] = {"count": m.count, "mean_rate": m.mean_rate()}
            elif isinstance(m, Timer):
                out[name] = {"count": m.count, "mean_s": m.mean_s(), "max_s": m.max_s}
            elif isinstance(m, Counter):
                out[name] = {"value": m.value}
            elif isinstance(m, Histogram):
                out[name] = m.snapshot_fields()
            elif isinstance(m, Gauge):
                out[name] = {"value": m.value, "max": m.max_value}
            else:
                out[name] = {"value": m()}
        return out
