"""SerialExecutor — the single-threaded node-thread discipline.

Reference parity: AffinityExecutor.ServiceAffinityExecutor
(node/utilities/AffinityExecutor.kt:1-118): nearly all node logic runs
serialized on one thread; `check_on_thread` asserts the discipline, and
`fetch_from` lets other threads run a closure on the node thread and wait.
This is the structural race defense the reference relies on instead of
sanitizers (SURVEY.md §5).
"""
from __future__ import annotations

import queue
import threading
from concurrent.futures import Future


class SerialExecutor:
    def __init__(self, name: str = "node-thread"):
        self._queue: "queue.Queue" = queue.Queue()
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        self._shutdown = False
        self._thread.start()

    def _run(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                return
            fn, fut = item
            try:
                result = fn()
            except BaseException as e:  # noqa: BLE001 — forwarded to the future
                if fut is not None:
                    fut.set_exception(e)
                continue
            if fut is not None:
                fut.set_result(result)

    # -- submission ----------------------------------------------------------
    def execute(self, fn) -> None:
        """Fire-and-forget on the node thread (executeASAP)."""
        if self.on_thread:
            fn()
            return
        self._queue.put((fn, None))

    def fetch_from(self, fn) -> Future:
        """Run on the node thread, return a Future of the result
        (AffinityExecutor.fetchFrom)."""
        if self.on_thread:
            fut: Future = Future()
            try:
                fut.set_result(fn())
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)
            return fut
        fut = Future()
        self._queue.put((fn, fut))
        return fut

    # -- assertions ----------------------------------------------------------
    @property
    def on_thread(self) -> bool:
        return threading.current_thread() is self._thread

    def check_on_thread(self) -> None:
        assert self.on_thread, \
            f"Expected to run on {self._thread.name}, was on " \
            f"{threading.current_thread().name}"

    def shutdown(self) -> None:
        self._queue.put(None)
        self._thread.join(timeout=5)
