from .metrics import MetricRegistry  # noqa: F401
