"""Retry with decorrelated-jitter backoff and a deadline budget.

The stack's transient-failure seams (TCP connect/probe, verifier worker
re-hello, raft client forwarding during an election) all need the same
shape: try, back off by a *jittered* growing delay so a thundering herd
of retriers decorrelates, give up when a deadline budget or attempt cap
is exhausted. The delay recurrence is the AWS "decorrelated jitter"
scheme: ``sleep = min(cap, uniform(base, prev * 3))``.

Every attempt is metered in a module-wide registry under
``Retry.Attempts`` (aggregate) and ``Retry.Attempts.<site>``; exhausted
retries mark ``Retry.GiveUps.<site>``. ``CordaRPCOps.metrics_snapshot``
merges :func:`snapshot` into the node registry so the counters ride
``/metrics`` and ``/api/metrics``.
"""
from __future__ import annotations

import logging
import random
import time
from dataclasses import dataclass
from typing import Callable, Iterator

from .metrics import MetricRegistry
from ..observability.slog import jlog

_log = logging.getLogger("corda_tpu.retry")

_REGISTRY = MetricRegistry()
_REGISTRY.meter("Retry.Attempts")    # pre-created: the family is always
_REGISTRY.meter("Retry.GiveUps")     # present in /metrics, even at zero


@dataclass(frozen=True)
class RetryPolicy:
    base_s: float = 0.05          # first / minimum backoff
    cap_s: float = 2.0            # per-sleep ceiling
    max_attempts: int = 5         # total tries (first call included)
    deadline_s: float | None = None  # total budget incl. projected sleep


DEFAULT_POLICY = RetryPolicy()


def registry() -> MetricRegistry:
    return _REGISTRY


def snapshot() -> dict:
    return _REGISTRY.snapshot()


def delays(policy: RetryPolicy = DEFAULT_POLICY,
           seed: int | None = None) -> Iterator[float]:
    """Endless decorrelated-jitter delay sequence — for call sites that
    own their retry loop (the TCP plane's async sender) and only need
    the backoff schedule."""
    rng = random.Random(seed)
    prev = policy.base_s
    while True:
        prev = min(policy.cap_s, rng.uniform(policy.base_s, prev * 3))
        yield prev


def retry_call(fn: Callable, *, site: str,
               policy: RetryPolicy = DEFAULT_POLICY,
               retry_on: tuple = (Exception,),
               seed: int | None = None,
               sleep: Callable[[float], None] = time.sleep,
               clock: Callable[[], float] = time.monotonic):
    """Call ``fn()`` until it returns, raising the last error once the
    attempt cap is hit or the next projected sleep would blow the
    deadline budget. ``site`` names the caller in the retry metrics."""
    attempts = _REGISTRY.meter(f"Retry.Attempts.{site}")
    total = _REGISTRY.get_metric("Retry.Attempts")
    start = clock()
    backoff = delays(policy, seed=seed)
    last: BaseException | None = None
    for attempt in range(1, policy.max_attempts + 1):
        attempts.mark()
        total.mark()
        try:
            return fn()
        except retry_on as e:
            last = e
            if attempt >= policy.max_attempts:
                break
            delay = next(backoff)
            if policy.deadline_s is not None and \
                    clock() - start + delay > policy.deadline_s:
                break
            jlog(_log, "retry.backoff", site=site, attempt=attempt,
                 delay_s=round(delay, 4), error=f"{type(e).__name__}: {e}")
            sleep(delay)
    _REGISTRY.meter(f"Retry.GiveUps.{site}").mark()
    _REGISTRY.get_metric("Retry.GiveUps").mark()
    jlog(_log, "retry.giveup", site=site, attempts=attempt,
         error=f"{type(last).__name__}: {last}")
    assert last is not None
    raise last
