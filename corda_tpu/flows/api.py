"""FlowLogic API: generator-based flows, IO request types, registries.

Reference parity:
- `FlowLogic` surface: send (FlowLogic.kt:142), receive/sendAndReceive
  (:87-106), subFlow (:156-168), waitForLedgerCommit (:231), progressTracker
  (:203).
- `UntrustworthyData` receive wrapper (type-checked unwrap).
- `@InitiatingFlow` / `@InitiatedBy` / `@StartableByRPC` annotations and the
  initiated-flow registry (AbstractNode.registerInitiatedFlows :292-342).

A flow body is written as a generator:

    @initiating_flow
    class Ping(FlowLogic):
        def __init__(self, peer): self.peer = peer
        def call(self):
            answer = yield SendAndReceive(self.peer, b"ping", bytes)
            return answer.unwrap(lambda d: d)

`yield` suspends the flow (a checkpoint is written); the state machine
resumes it with the response. Sub-flows compose with `yield from`:

    result = yield from self.sub_flow(OtherFlow(...))
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Generator

from ..core.identity import Party


class FlowException(Exception):
    """Error that propagates across a session to the counterparty
    (reference FlowException — surfaces at the peer's receive)."""


class FlowTimeoutException(FlowException):
    """A Receive/SendAndReceive with ``timeout_s`` expired before the peer
    replied (thrown at the yield site; the session stays usable)."""


# ---------------------------------------------------------------------------
# IO request types (FlowIORequest.kt analog)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Send:
    party: Party
    payload: Any


@dataclass(frozen=True)
class Receive:
    party: Party
    expected_type: type = object
    #: optional deadline (seconds on the node's clock): on expiry a
    #: FlowTimeoutException is thrown at the yield site instead of parking
    #: forever (ClockUtils fiber-aware deadline parity)
    timeout_s: float | None = None


@dataclass(frozen=True)
class SendAndReceive:
    party: Party
    payload: Any
    expected_type: type = object
    timeout_s: float | None = None   # see Receive.timeout_s


@dataclass(frozen=True)
class WaitForLedgerCommit:
    tx_id: Any  # SecureHash


@dataclass(frozen=True)
class Sleep:
    """Suspend the flow for ``seconds`` on the NODE's clock without blocking
    the node thread (the reference's fiber-aware ClockUtils.awaitWithDeadline,
    ClockUtils.kt): a timer — or a test clock advance — resumes it. A sleep
    interrupted by a restart restarts in full on restore (the deadline is
    re-armed relative to the restored clock)."""

    seconds: float


@dataclass(frozen=True)
class Verify:
    """Suspend until the node's TransactionVerifierService resolves the
    verification of ``stx`` — the reference parks the flow fiber on the
    verifier future (FlowStateMachineImpl.kt:379-393 via Services.kt:544-550),
    so a Tpu- or OutOfProcess-backed node verifies OFF the node thread and
    concurrently-suspended flows' signatures coalesce into shared device
    batches. The flow resumes with None on success; a verification failure
    is thrown at the yield site with its original type (preserved across
    checkpoint replay via the typed error log entry)."""

    stx: Any
    check_sufficient_signatures: bool = True


@dataclass(frozen=True)
class VerifyMany:
    """Suspend until the verifier service resolves ALL of ``stxs`` — one
    yield site submits the whole wave, so N transactions' signatures land
    in the batcher concurrently instead of one service round-trip per
    link (the wave-based ResolveTransactionsFlow discipline). Resumes with
    None when every verification succeeds; the FIRST failure (submission
    order) is thrown at the yield site with its original type."""

    stxs: tuple
    check_sufficient_signatures: bool = True


@dataclass(frozen=True)
class AwaitFuture:
    """Suspend until the Future returned by ``producer()`` resolves — the
    generic park-on-a-future primitive (the reference parks fibers on
    ListenableFutures). ``producer`` runs on the node thread at the yield
    site; it must return a concurrent.futures.Future (or None, which
    resumes immediately with None). The flow resumes with the future's
    (checkpoint-serializable) result, or the future's exception is thrown
    at the yield site with its original type preserved across replay.

    On checkpoint replay the producer is RE-EXECUTED, so it must be
    idempotent — the group-commit path qualifies: re-submitting a
    committed transaction's refs is absorbed by find_conflicts' same-tx
    rule.

    ``purpose`` names what the flow is waiting FOR — it becomes the
    ``wait_kind`` tag on the park's wait-state span, so the critical-path
    extractor can attribute the parked time to a component instead of an
    anonymous future."""

    producer: Callable[[], Any]
    purpose: str = "future"


@dataclass(frozen=True)
class ExecuteOnce:
    """Run a local, possibly non-deterministic computation exactly once and
    checkpoint its (serializable) result: on replay the recorded value is
    returned instead of re-running the producer. Required for anything that
    reads mutable node state before a suspension — vault coin selection,
    fresh-key generation, clock reads (the replay-determinism contract,
    corda_tpu.flows docstring)."""

    producer: Callable[[], Any]


class UntrustworthyData:
    """Wrapper forcing explicit unwrap of peer-supplied data
    (core FlowLogic receive semantics)."""

    __slots__ = ("_data",)

    def __init__(self, data):
        self._data = data

    def unwrap(self, validator: Callable[[Any], Any]):
        return validator(self._data)

    def __repr__(self):
        return f"UntrustworthyData({type(self._data).__name__})"


# ---------------------------------------------------------------------------
# FlowLogic
# ---------------------------------------------------------------------------

class FlowLogic:
    """Base class for all flows. Subclasses implement `call()` as a generator
    (or a plain function for purely-local flows)."""

    # injected by the state machine before `call()` runs
    state_machine = None  # FlowStateMachine
    service_hub = None    # ServiceHub

    progress_tracker = None

    def call(self) -> Generator:
        raise NotImplementedError

    # -- composition ---------------------------------------------------------
    def sub_flow(self, flow: "FlowLogic") -> Generator:
        """Run a sub-flow inline on the same state machine
        (FlowLogic.kt:156-168). Use as `yield from self.sub_flow(f)`.

        An @initiating_flow sub-flow gets its own *session group*: sessions it
        opens are distinct from the parent's even toward the same party, and
        its SessionInits carry the sub-flow's class name so the peer picks the
        right handler — the reference's (FlowLogic, Party) session keying.
        The group id is a deterministic counter, so replay-based restore
        reconstructs identical keys."""
        flow.state_machine = self.state_machine
        flow.service_hub = self.service_hub
        gen = flow.call()
        if not hasattr(gen, "send"):  # non-generator call(): plain result
            return gen
        fsm = self.state_machine
        own_group = getattr(type(flow), "_initiating", False) and fsm is not None
        if own_group:
            fsm.session_group_counter += 1
            fsm.session_group_stack.append(
                (fsm.session_group_counter, flow_name(type(flow))))
        try:
            result = yield from gen
        finally:
            if own_group:
                fsm.session_group_stack.pop()
        return result

    # -- convenience wrappers (each is a single yield site) ------------------
    def send(self, party: Party, payload) -> Generator:
        yield Send(party, payload)

    def receive(self, party: Party, expected_type: type = object) -> Generator:
        data = yield Receive(party, expected_type)
        return data

    def send_and_receive(self, party: Party, payload,
                         expected_type: type = object) -> Generator:
        data = yield SendAndReceive(party, payload, expected_type)
        return data

    def send_and_receive_with_retry(self, party: Party, payload,
                                    expected_type: type = object,
                                    attempts: int = 3) -> Generator:
        """Retry the exchange on session failure — for IDEMPOTENT requests to
        clustered services whose members may fail over mid-request
        (FlowLogic.kt:106-113 sendAndReceiveWithRetry)."""
        last: Exception | None = None
        for _ in range(attempts):
            try:
                data = yield SendAndReceive(party, payload, expected_type)
                return data
            except FlowException as e:
                last = e
                # the failed session is dead; drop it (routing index included)
                # so the retry opens a FRESH one and a straggler reply on the
                # old session id can't be mistaken for the new attempt's
                fsm = self.state_machine
                if fsm is not None:
                    fsm.smm.discard_session(fsm, fsm.current_group[0],
                                            str(party.name))
        raise last if last is not None else FlowException("retry exhausted")

    def wait_for_ledger_commit(self, tx_id) -> Generator:
        stx = yield WaitForLedgerCommit(tx_id)
        return stx

    def record(self, producer: Callable[[], Any]) -> Generator:
        """`value = yield from self.record(fn)` — run fn once, checkpoint the
        result (see ExecuteOnce)."""
        value = yield ExecuteOnce(producer)
        return value

    @property
    def run_id(self):
        return self.state_machine.run_id if self.state_machine else None

    @property
    def our_identity(self) -> Party:
        return self.service_hub.my_info.legal_identity


# ---------------------------------------------------------------------------
# Annotations / registries
# ---------------------------------------------------------------------------

_INITIATED_BY: dict[str, Callable[[Party], FlowLogic]] = {}
_RPC_STARTABLE: dict[str, type] = {}


def flow_name(cls: type) -> str:
    return f"{cls.__module__}.{cls.__qualname__}"


def initiating_flow(cls: type) -> type:
    """@InitiatingFlow — marks a flow that opens sessions with new peers."""
    cls._initiating = True
    return cls


def InitiatingFlow(cls: type) -> type:  # reference-style alias
    return initiating_flow(cls)


def initiated_by(initiator_cls: type):
    """@InitiatedBy(Initiator) — registers a responder factory keyed by the
    initiator's flow name (AbstractNode.kt:292-342 registration)."""

    def decorate(cls: type) -> type:
        _INITIATED_BY[flow_name(initiator_cls)] = cls
        cls._initiated_by = initiator_cls
        return cls

    return decorate


def startable_by_rpc(cls: type) -> type:
    _RPC_STARTABLE[flow_name(cls)] = cls
    cls._startable_by_rpc = True
    return cls


def get_initiated_flow_factory(initiator_name: str):
    return _INITIATED_BY.get(initiator_name)


def rpc_startable_flows() -> dict[str, type]:
    return dict(_RPC_STARTABLE)


# ---------------------------------------------------------------------------
# Session handle used by the state machine
# ---------------------------------------------------------------------------

def _fresh_session_id() -> int:
    """Random 63-bit session id (reference random63BitValue — restart-safe,
    unlike a process-local counter)."""
    import secrets
    return secrets.randbits(63)


@dataclass
class FlowSession:
    """One side of a flow session (statemachine session state)."""

    peer: Party
    our_session_id: int = field(default_factory=_fresh_session_id)
    peer_session_id: int | None = None
    state: str = "initiating"  # initiating | open | ended | errored
    received: list = field(default_factory=list)  # queued inbound payloads
    error: Exception | None = None
    group: int = 0                                # sub-flow session group
    pending_out: list = field(default_factory=list)  # buffered pre-confirm sends
