"""The flow framework — the ledger's programming model.

Reference parity: FlowLogic (core/flows/FlowLogic.kt:37 — send/receive/
sendAndReceive/subFlow/waitForLedgerCommit), @InitiatingFlow/@InitiatedBy/
@StartableByRPC annotations, and the session protocol semantics of
node/services/statemachine.

TPU-host-native redesign (SURVEY.md §7 phase 3): flows are Python
*generators* — `call()` yields FlowIORequest objects and receives responses
at the yield site. Checkpointing uses **deterministic replay** (an
event-sourced response log) instead of continuation serialization: a
checkpoint is (flow reference, constructor args, ordered responses consumed
so far); resume re-executes `call()` feeding the log back until it catches
up, then continues live. No bytecode weaving, no frame capture — the
at-suspend atomic checkpoint+effects semantics of
FlowStateMachineImpl.kt:379-393 are kept, the mechanism is idiomatic Python.
The determinism contract this imposes on flow code matches what the
reference already demands of @Suspendable methods (resumable on another JVM).
"""
from .api import (  # noqa: F401
    FlowException,
    FlowLogic,
    FlowSession,
    InitiatingFlow,
    Receive,
    Send,
    SendAndReceive,
    UntrustworthyData,
    WaitForLedgerCommit,
    initiated_by,
    initiating_flow,
    startable_by_rpc,
    get_initiated_flow_factory,
    rpc_startable_flows,
)
from .confidential import (  # noqa: F401
    TransactionKeyFlow,
    TransactionKeyHandler,
)
