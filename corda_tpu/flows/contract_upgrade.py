"""Contract upgrade: migrate a state to a new contract version with every
participant's prior authorisation.

Reference parity: ContractUpgradeFlow.kt (+ UpgradedContract in core): each
participant AUTHORISES the upgrade out-of-band first (recorded against the
state ref); the instigator then proposes an upgrade transaction whose
outputs are exactly `upgraded_contract.upgrade(input_state)`; acceptors
refuse anything they have not authorised or that rewrites state beyond the
upgrade function; everyone signs, the old notary notarises, finality
broadcasts. The transaction carries an UpgradeCommand naming the new
contract, which the upgraded contract's verify must accept.
"""
from __future__ import annotations

from dataclasses import dataclass

from ..core.contracts.structures import (Command, CommandData, StateAndRef,
                                         StateRef, TransactionState)
from ..core.crypto.signatures import DigitalSignatureWithKey
from ..core.serialization import register_type, serializable
from ..core.transactions.signed import SignedTransaction
from ..core.transactions.wire import WireTransaction
from .api import (FlowException, FlowLogic, Receive, Send, SendAndReceive,
                  initiating_flow)
from .library import FinalityFlow, _party_by_key


class UpgradedContract:
    """Interface for the new contract version (core UpgradedContract):
    `legacy_contract_name` names what it upgrades FROM, `upgrade(old_state)`
    maps old state data to new."""

    legacy_contract_name: str = ""

    def upgrade(self, old_state):
        raise NotImplementedError


@serializable("UpgradeCommand", to_fields=lambda c: [c.upgraded_contract_name],
              from_fields=lambda f: UpgradeCommand(f[0]))
@dataclass(frozen=True)
class UpgradeCommand(CommandData):
    upgraded_contract_name: str


@dataclass(frozen=True)
class UpgradeProposal:
    stx: object
    ref: object
    upgraded_contract_name: str


register_type("flows.UpgradeProposal", UpgradeProposal)


def contract_name(contract) -> str:
    cls = contract if isinstance(contract, type) else type(contract)
    return f"{cls.__module__}.{cls.__qualname__}"


def authorise_contract_upgrade(hub, state_and_ref: StateAndRef,
                               upgraded_contract) -> None:
    """Record consent to upgrade `state_and_ref` to `upgraded_contract`
    (CordaRPCOps.authoriseContractUpgrade)."""
    if not hasattr(hub, "contract_upgrade_authorisations"):
        hub.contract_upgrade_authorisations = {}
    hub.contract_upgrade_authorisations[state_and_ref.ref] = \
        contract_name(upgraded_contract)


def deauthorise_contract_upgrade(hub, state_and_ref: StateAndRef) -> None:
    getattr(hub, "contract_upgrade_authorisations", {}).pop(
        state_and_ref.ref, None)


class ContractUpgradeException(FlowException):
    pass


@initiating_flow
class ContractUpgradeFlow(FlowLogic):
    """Instigator: build the upgrade tx, collect acceptances, finalise."""

    def __init__(self, state_and_ref: StateAndRef, upgraded_contract):
        self.state_and_ref = state_and_ref
        self.upgraded_contract = upgraded_contract

    def call(self):
        hub = self.service_hub
        old = self.state_and_ref.state
        new_data = self.upgraded_contract.upgrade(old.data)
        name = contract_name(self.upgraded_contract)
        participants = {getattr(p, "owning_key", p)
                        for p in old.data.participants}
        wtx = WireTransaction(
            inputs=(self.state_and_ref.ref,),
            outputs=(TransactionState(new_data, old.notary, old.encumbrance),),
            commands=(Command(UpgradeCommand(name), tuple(sorted(participants))),),
            notary=old.notary,
            must_sign=tuple(sorted(participants | {old.notary.owning_key})))
        stx = hub.sign_initial_transaction(wtx)
        our_keys = hub.key_management.keys
        for key in participants:
            if any(leaf in our_keys for leaf in key.keys):
                continue
            party = _party_by_key(hub, key)
            if party is None:
                raise ContractUpgradeException(
                    f"No well-known party for {key.to_string_short()}")
            resp = yield SendAndReceive(
                party, UpgradeProposal(stx, self.state_and_ref.ref, name),
                DigitalSignatureWithKey)

            def validate(sig, _key=key):
                sig.verify(stx.id.bytes)
                if not _key.is_fulfilled_by({sig.by}):
                    raise ContractUpgradeException(
                        "Acceptance signed by an unexpected key")
                return sig

            stx = stx.plus(resp.unwrap(validate))
        final = yield from self.sub_flow(FinalityFlow(
            stx, [p for p in (_party_by_key(hub, k) for k in participants)
                  if p is not None]))
        return StateAndRef(final.tx.outputs[0], StateRef(final.id, 0))


class ContractUpgradeAcceptor(FlowLogic):
    """Acceptor: sign only upgrades we authorised, exactly as proposed."""

    def __init__(self, peer):
        self.peer = peer

    def call(self):
        req = yield Receive(self.peer, UpgradeProposal)
        proposal = req.unwrap(
            lambda r: r if isinstance(r, UpgradeProposal) else _refuse())
        hub = self.service_hub
        authorised = getattr(hub, "contract_upgrade_authorisations", {}).get(
            proposal.ref)
        if authorised != proposal.upgraded_contract_name:
            raise ContractUpgradeException(
                f"Upgrade of {proposal.ref} to "
                f"{proposal.upgraded_contract_name} is not authorised")
        stx: SignedTransaction = proposal.stx
        wtx = stx.tx
        if len(wtx.inputs) != 1 or wtx.inputs[0] != proposal.ref \
                or len(wtx.outputs) != 1:
            raise ContractUpgradeException("Malformed upgrade transaction")
        known = hub.load_state(proposal.ref)
        if known is None:
            raise ContractUpgradeException("Unknown state being upgraded")
        # rebuild the expected output with OUR copy of the upgrade function
        upgraded = _resolve_contract(proposal.upgraded_contract_name)
        if contract_name(known.data.contract) != upgraded.legacy_contract_name:
            raise ContractUpgradeException(
                "Upgrade does not apply to the state's current contract")
        expected = upgraded.upgrade(known.data)
        if wtx.outputs[0].data != expected or wtx.outputs[0].notary != known.notary:
            raise ContractUpgradeException(
                "Proposed output is not the authorised upgrade of the input")
        stx.check_signatures_are_valid()
        our_key = next((leaf for k in wtx.must_sign for leaf in k.keys
                        if leaf in hub.key_management.keys), None)
        if our_key is None:
            raise ContractUpgradeException("Our signature is not required")
        yield Send(self.peer, hub.key_management.sign(stx.id.bytes, our_key))
        return None


def _resolve_contract(name: str):
    from ..node.statemachine import _import_flow_class
    cls = _import_flow_class(name)
    return cls() if isinstance(cls, type) else cls


def _refuse():
    raise ContractUpgradeException("Malformed upgrade proposal")


def install_contract_upgrade_acceptor(smm) -> None:
    from .api import flow_name
    smm.register_flow_factory(flow_name(ContractUpgradeFlow),
                              ContractUpgradeAcceptor)
