"""Confidential identities: the transaction-key exchange flow.

Reference parity: TransactionKeyFlow (core flows, SURVEY.md §2.1 flow list)
— before building a transaction, counterparties swap FRESH one-time keys so
on-ledger states reference anonymous identities rather than well-known
ones. Each side proves ownership of its fresh key by signing it (plus its
X.500 name) with its well-known identity key; the peer validates the
attestation and records the mapping in its identity service
(registerAnonymousIdentity). Returns the {party: AnonymousParty} map both
sides agree on.
"""
from __future__ import annotations

from ..core.identity import AnonymousParty, Party
from .api import (FlowException, FlowLogic, Receive, Send, initiated_by,
                  initiating_flow)


def _exchange_payload(hub, anon_key):
    sig = hub.sign(
        hub.identity_service.ownership_content(
            anon_key, hub.my_info.legal_identity.name))
    return [anon_key, sig.bytes]


def _accept_payload(hub, peer: Party, payload) -> AnonymousParty:
    key, sig_bytes = payload
    anon = AnonymousParty(key)
    try:
        hub.identity_service.verify_and_register_anonymous(anon, peer,
                                                           sig_bytes)
    except Exception as e:
        raise FlowException(
            f"Invalid anonymous-identity attestation from {peer.name}: {e}")
    return anon


@initiating_flow
class TransactionKeyFlow(FlowLogic):
    """Initiator: send our fresh anonymous identity, receive the peer's."""

    def __init__(self, other_side: Party):
        self.other_side = other_side

    def call(self):
        hub = self.service_hub
        anon_key = yield from self.record(
            lambda: hub.key_management.fresh_key().public)
        yield Send(self.other_side, _exchange_payload(hub, anon_key))
        resp = yield Receive(self.other_side, list)
        theirs = _accept_payload(hub, self.other_side,
                                 resp.unwrap(lambda d: d))
        return {hub.my_info.legal_identity: AnonymousParty(anon_key),
                self.other_side: theirs}


@initiated_by(TransactionKeyFlow)
class TransactionKeyHandler(FlowLogic):
    """Responder: receive the initiator's identity, reply with ours."""

    def __init__(self, peer: Party):
        self.peer = peer

    def call(self):
        hub = self.service_hub
        req = yield Receive(self.peer, list)
        theirs = _accept_payload(hub, self.peer, req.unwrap(lambda d: d))
        anon_key = yield from self.record(
            lambda: hub.key_management.fresh_key().public)
        yield Send(self.peer, _exchange_payload(hub, anon_key))
        return {hub.my_info.legal_identity: AnonymousParty(anon_key),
                self.peer: theirs}
