"""Library flows: notarisation, finality, broadcast, resolution, signing.

Reference parity (core/src/main/kotlin/net/corda/core/flows/):
- NotaryFlow.Client/Service (NotaryFlow.kt:31-120)
- FinalityFlow (FinalityFlow.kt:36,86-98): notarise → record → broadcast
- BroadcastTransactionFlow + NotifyTransactionHandler (CoreFlowHandlers.kt)
- FetchTransactionsFlow / FetchDataFlow (hash-addressed download + check)
- ResolveTransactionsFlow (dependency-graph walk, topological order, 5000-tx
  cap — ResolveTransactionsFlow.kt:31,40,98,134)
- CollectSignaturesFlow / SignTransactionFlow (CollectSignaturesFlow.kt:1-258)
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from ..core.crypto.signatures import DigitalSignatureWithKey
from ..core.serialization import register_type
from ..core.transactions.signed import SignedTransaction
from .api import (AwaitFuture, FlowException, FlowLogic, Receive, Send,
                  SendAndReceive, Verify, VerifyMany, initiating_flow)

MAX_RESOLVE_TRANSACTIONS = 5000  # ResolveTransactionsFlow.kt partial-tx cap


# ---------------------------------------------------------------------------
# Wire payloads
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class NotarisationRequest:
    stx: Any                  # SignedTransaction (validating) or filtered form


@dataclass(frozen=True)
class FetchTransactionsRequest:
    tx_ids: tuple             # SecureHash...


@dataclass(frozen=True)
class FetchAttachmentsRequest:
    att_ids: tuple           # SecureHash...


@dataclass(frozen=True)
class NotifyTxRequest:
    stx: Any


@dataclass(frozen=True)
class SignTransactionRequest:
    stx: Any


for _cls in (NotarisationRequest, FetchTransactionsRequest,
             FetchAttachmentsRequest, NotifyTxRequest, SignTransactionRequest):
    register_type(f"flows.{_cls.__name__}", _cls)


class NotaryException(FlowException):
    """Conflict or rejection from the notary (NotaryException.Conflict)."""


# ---------------------------------------------------------------------------
# Notarisation
# ---------------------------------------------------------------------------

@initiating_flow
class NotaryFlow(FlowLogic):
    """Client side (NotaryFlow.Client, NotaryFlow.kt:31-44): request a notary
    signature over the transaction; raises NotaryException on conflict."""

    def __init__(self, stx: SignedTransaction):
        self.stx = stx

    def call(self):
        notary = self.stx.notary
        if notary is None:
            raise FlowException("Transaction has no notary set")
        try:
            resp = yield SendAndReceive(notary, NotarisationRequest(self.stx),
                                        DigitalSignatureWithKey)
        except FlowException as e:
            raise NotaryException(str(e)) from e

        def validate(sig):
            if not isinstance(sig, DigitalSignatureWithKey):
                raise FlowException(f"Notary returned {type(sig).__name__}")
            if not notary.owning_key.is_fulfilled_by(sig.by):
                raise FlowException("Notary signature by an unexpected key")
            sig.verify(self.stx.id.bytes)
            return sig

        return [resp.unwrap(validate)]


class NotaryServiceFlow(FlowLogic):
    """Service side (NotaryFlow.Service, NotaryFlow.kt:95-120), instantiated
    per request by the notary node's installed NotaryService. Validating
    services fully verify first (ValidatingNotaryFlow); both check the time
    window and commit input uniqueness before signing."""

    def __init__(self, peer, service):
        self.peer = peer
        self.service = service

    def call(self):
        req = yield Receive(self.peer, NotarisationRequest)
        stx = req.unwrap(lambda r: r.stx if isinstance(r, NotarisationRequest)
                         else _reject("Expected a NotarisationRequest"))
        if self.service.validating:
            # resolve dependencies from the requester, then fully verify
            yield from self.sub_flow(ResolveTransactionsFlow(
                self.peer, stx=stx))
            yield Verify(stx, check_sufficient_signatures=False)
        if not self.service.time_window_checker.is_valid(stx.tx.time_window):
            raise FlowException("Transaction time-window is outside tolerance")
        try:
            if getattr(self.service, "supports_async_commit", False):
                # group-commit path: park the flow on the GroupCommitter's
                # future instead of blocking the notary node thread for a
                # full consensus round — concurrently suspended requests
                # coalesce into one put_all_batch raft append
                trace_ctx = getattr(self.state_machine, "trace_ctx", None)
                yield AwaitFuture(lambda: self.service.commit_async(
                    stx.inputs, stx.id, str(self.peer.name),
                    trace_ctx=trace_ctx), purpose="notary.commit")
            elif getattr(self.service, "supports_trace_ctx", False):
                self.service.commit(
                    stx.inputs, stx.id, str(self.peer.name),
                    trace_ctx=getattr(self.state_machine, "trace_ctx", None))
            else:
                self.service.commit(stx.inputs, stx.id, str(self.peer.name))
        except Exception as e:
            raise FlowException(str(e)) from e
        sig = self.service.sign_tx_id(stx.id)
        yield Send(self.peer, sig)
        return None


def _reject(msg: str):
    raise FlowException(msg)


# ---------------------------------------------------------------------------
# Fetch / resolve
# ---------------------------------------------------------------------------

@initiating_flow
class FetchTransactionsFlow(FlowLogic):
    """Download transactions by id from a peer, verifying each returned blob
    hashes to its requested id (FetchDataFlow's maybeCheckHash)."""

    def __init__(self, peer, tx_ids):
        self.peer = peer
        self.tx_ids = tuple(tx_ids)

    def call(self):
        from_disk, to_fetch = [], []
        for tx_id in self.tx_ids:
            stx = self.service_hub.storage.get_transaction(tx_id)
            (from_disk if stx is not None else to_fetch).append(stx or tx_id)
        if not to_fetch:
            return from_disk
        resp = yield SendAndReceive(self.peer,
                                    FetchTransactionsRequest(tuple(to_fetch)),
                                    list)

        def validate(stxs):
            if len(stxs) != len(to_fetch):
                raise FlowException("Peer returned wrong number of transactions")
            for tx_id, stx in zip(to_fetch, stxs):
                if not isinstance(stx, SignedTransaction) or stx.id != tx_id:
                    raise FlowException(
                        f"Peer returned a transaction that hashes to {stx.id} "
                        f"instead of the requested {tx_id}")
            return list(stxs)

        return from_disk + resp.unwrap(validate)


class FetchTransactionsHandler(FlowLogic):
    """Serves FetchTransactionsFlow requests from local storage — installed on
    every node (installCoreFlows, AbstractNode.kt:285)."""

    def __init__(self, peer):
        self.peer = peer

    def call(self):
        req = yield Receive(self.peer, FetchTransactionsRequest)
        tx_ids = req.unwrap(lambda r: r.tx_ids)
        out = []
        for tx_id in tx_ids:
            stx = self.service_hub.storage.get_transaction(tx_id)
            if stx is None:
                raise FlowException(f"Transaction {tx_id} not found")
            out.append(stx)
        yield Send(self.peer, out)
        return None


@initiating_flow
class FetchAttachmentsFlow(FlowLogic):
    """Download attachments by hash from a peer, verifying content hashes
    (FetchAttachmentsFlow: the hash IS the id, so tampering is detectable)."""

    def __init__(self, peer, att_ids):
        self.peer = peer
        self.att_ids = tuple(att_ids)

    def call(self):
        hub = self.service_hub
        to_fetch = [a for a in self.att_ids if not hub.attachments.has_attachment(a)]
        if to_fetch:
            resp = yield SendAndReceive(
                self.peer, FetchAttachmentsRequest(tuple(to_fetch)), list)

            def validate(blobs):
                if len(blobs) != len(to_fetch):
                    raise FlowException("Peer returned wrong attachment count")
                from ..core.crypto.secure_hash import SecureHash
                for att_id, blob in zip(to_fetch, blobs):
                    if SecureHash.sha256(blob) != att_id:
                        raise FlowException(
                            f"Attachment content does not hash to {att_id}")
                return blobs

            for blob in resp.unwrap(validate):
                hub.attachments.import_attachment(blob)
        return [hub.attachments.open_attachment(a) for a in self.att_ids]


class FetchAttachmentsHandler(FlowLogic):
    def __init__(self, peer):
        self.peer = peer

    def call(self):
        req = yield Receive(self.peer, FetchAttachmentsRequest)
        att_ids = req.unwrap(lambda r: r.att_ids)
        hub = self.service_hub
        blobs = []
        for att_id in att_ids:
            att = hub.attachments.open_attachment(att_id)
            if att is None:
                raise FlowException(f"Attachment {att_id} not found")
            blobs.append(att.data)
        yield Send(self.peer, blobs)
        return None


FETCH_PAGE = 500  # tx ids per FetchTransactionsFlow request within a wave


@initiating_flow
class ResolveTransactionsFlow(FlowLogic):
    """Wave-based dependency download + verify+record
    (ResolveTransactionsFlow.kt:31-134, vectorized): instead of walking the
    graph link-by-link, each round fetches the ENTIRE unseen frontier as
    one batched request (paged at FETCH_PAGE ids), so a depth-D chain costs
    D round trips, not D×(chain width). Verification then runs in
    topological WAVES — every member of a wave has its dependencies already
    recorded, so the whole wave is submitted to the verifier service at
    once (VerifyMany) and its signatures coalesce into shared device
    batches. Hard cap of 5000 transactions per walk."""

    def __init__(self, peer, tx_ids=None, stx: SignedTransaction | None = None):
        self.peer = peer
        self.tx_ids = tuple(tx_ids) if tx_ids else ()
        self.stx = stx

    def call(self):
        hub = self.service_hub
        frontier = list(self.tx_ids)
        if self.stx is not None:
            frontier.extend(ref.txhash for ref in self.stx.inputs)
        fetched: dict = {}
        seen = set(frontier)
        queue = [tx_id for tx_id in frontier
                 if hub.storage.get_transaction(tx_id) is None]
        while queue:
            if len(fetched) + len(queue) > MAX_RESOLVE_TRANSACTIONS:
                raise FlowException(
                    f"Transaction resolution exceeds the {MAX_RESOLVE_TRANSACTIONS} limit")
            # one wave = the whole current frontier; page only to bound the
            # size of a single wire message
            wave, queue = queue, []
            stxs = []
            for i in range(0, len(wave), FETCH_PAGE):
                page = yield from self.sub_flow(
                    FetchTransactionsFlow(self.peer, wave[i:i + FETCH_PAGE]))
                stxs.extend(page)
            for stx in stxs:
                fetched[stx.id] = stx
                for ref in stx.inputs:
                    dep = ref.txhash
                    if dep not in seen:
                        seen.add(dep)
                        if hub.storage.get_transaction(dep) is None:
                            queue.append(dep)
        # attachments referenced anywhere in the resolved set must be local
        # before verification can open them (FetchAttachmentsFlow leg of
        # ResolveTransactionsFlow.kt)
        att_ids = {a for stx in fetched.values() for a in stx.tx.attachments}
        if self.stx is not None:
            att_ids |= set(self.stx.tx.attachments)
        missing = [a for a in att_ids if not hub.attachments.has_attachment(a)]
        if missing:
            yield from self.sub_flow(FetchAttachmentsFlow(self.peer, missing))
        # verify in topological waves: all of wave N's dependencies were
        # recorded by waves < N, and within a wave the transactions are
        # independent — so the whole wave verifies concurrently
        ordered = []
        for wave in _topological_waves(fetched):
            yield VerifyMany(tuple(wave), check_sufficient_signatures=False)
            for stx in wave:
                hub.record_transactions(stx)
                ordered.append(stx)
        return [stx.id for stx in ordered]


def _topological_waves(txs: dict) -> list:
    """Kahn's algorithm by levels: wave k = every tx whose dependencies all
    live in waves < k (dependency-free members first). Flattening the waves
    yields a valid topological order."""
    pending = dict(txs)
    waves = []
    while pending:
        wave = [stx for tx_id, stx in pending.items()
                if all(ref.txhash not in pending for ref in stx.inputs)]
        if not wave:
            raise FlowException("Transaction dependency cycle detected")
        for stx in wave:
            del pending[stx.id]
        waves.append(wave)
    return waves


def _topological_order(txs: dict) -> list:
    """Dependencies-first flat order (kept for callers/tests that assert on
    the order directly)."""
    return [stx for wave in _topological_waves(txs) for stx in wave]


# ---------------------------------------------------------------------------
# Broadcast / finality
# ---------------------------------------------------------------------------

@initiating_flow
class BroadcastTransactionFlow(FlowLogic):
    """Send a finalised transaction to each participant
    (BroadcastTransactionFlow → NotifyTransactionHandler)."""

    def __init__(self, stx: SignedTransaction, participants):
        self.stx = stx
        self.participants = tuple(participants)

    def call(self):
        me = str(self.service_hub.my_info.legal_identity.name)
        sent = {me}
        undelivered = []
        for party in self.participants:
            if str(party.name) in sent:
                continue
            sent.add(str(party.name))
            # ACKNOWLEDGED delivery: the reference rides durable broker
            # queues, so a recipient that is down still gets the broadcast
            # on recovery; the TCP plane has no such durability, so the
            # sender waits until the recipient has RECORDED the transaction
            # — a finalised payment can no longer vanish with a crashed
            # recipient's in-flight frame. A failed recipient must not
            # starve the REMAINING recipients (the transaction is already
            # final): every delivery is attempted, then the undelivered
            # set surfaces as one error.
            try:
                resp = yield SendAndReceive(party, NotifyTxRequest(self.stx),
                                            bytes)
                resp.unwrap(lambda ack: ack)
            except FlowException as e:
                undelivered.append((party, str(e)))
        if undelivered:
            detail = "; ".join(f"{p.name}: {reason}"
                               for p, reason in undelivered)
            raise FlowException(
                f"transaction {self.stx.id.prefix_chars()} is FINAL but "
                f"could not be delivered to: {detail}")
        return None


class NotifyTransactionHandler(FlowLogic):
    """Receives a broadcast transaction: resolve deps from the sender, verify,
    record, acknowledge (CoreFlowHandlers.kt NotifyTransactionHandler)."""

    def __init__(self, peer):
        self.peer = peer

    def call(self):
        req = yield Receive(self.peer, NotifyTxRequest)
        stx = req.unwrap(lambda r: r.stx)
        yield from self.sub_flow(ResolveTransactionsFlow(self.peer, stx=stx))
        yield Verify(stx, check_sufficient_signatures=False)
        self.service_hub.record_transactions(stx)
        yield Send(self.peer, b"ack")
        return None


@initiating_flow
class FinalityFlow(FlowLogic):
    """Notarise (if needed), record locally, broadcast to participants
    (FinalityFlow.kt:36,86-98)."""

    def __init__(self, stx: SignedTransaction, extra_recipients=()):
        self.stx = stx
        self.extra_recipients = tuple(extra_recipients)

    def call(self):
        import time as _time
        hub = self.service_hub
        stx = self.stx
        needs_notary = stx.notary is not None and (
            len(stx.inputs) > 0 or stx.tx.time_window is not None)
        if needs_notary:
            # client-observed notarisation round trip (request → notary
            # uniqueness/raft commit → signature back) — the commit path's
            # dominant wait, so it gets its own node histogram alongside
            # the notary-side notary_uniqueness_seconds stage
            t0 = _time.perf_counter()
            notary_sigs = yield from self.sub_flow(NotaryFlow(stx))
            stx = stx.plus(*notary_sigs)
            monitoring = getattr(hub, "monitoring", None)
            if monitoring is not None:
                sm = getattr(self, "state_machine", None)
                ctx = getattr(sm, "trace_ctx", None)
                monitoring.histogram("notarise_seconds").update(
                    _time.perf_counter() - t0,
                    trace_id=getattr(ctx, "trace_id", None))
        hub.record_transactions(stx)
        participants = self._participant_parties(stx)
        yield from self.sub_flow(
            BroadcastTransactionFlow(stx, participants + list(self.extra_recipients)))
        return stx

    def _participant_parties(self, stx):
        hub = self.service_hub
        parties = []
        seen = set()
        for out in stx.tx.outputs:
            for key in getattr(out.data, "participants", []):
                owning = getattr(key, "owning_key", key)
                party = hub.identity_service.party_from_key(owning) \
                    if hasattr(hub.identity_service, "party_from_key") else None
                if party is None:
                    party = _party_by_key(hub, owning)
                if party is not None and party.owning_key not in seen:
                    seen.add(party.owning_key)
                    parties.append(party)
        return parties


@initiating_flow
class ManualFinalityFlow(FinalityFlow):
    """FinalityFlow that broadcasts ONLY to the explicitly named recipients —
    no participant derivation (core ManualFinalityFlow: used when states'
    participants cannot be resolved to well-known parties, e.g. anonymous
    or externally-held keys)."""

    def __init__(self, stx: SignedTransaction, recipients):
        super().__init__(stx, extra_recipients=recipients)

    def _participant_parties(self, stx):
        return []


def _party_by_key(hub, key):
    for info in hub.network_map_cache.all_nodes():
        if info.legal_identity.owning_key == key:
            return info.legal_identity
    return None


# ---------------------------------------------------------------------------
# Signature collection
# ---------------------------------------------------------------------------

@initiating_flow
class CollectSignaturesFlow(FlowLogic):
    """Collect signatures from every required signer other than ourselves and
    the notary (CollectSignaturesFlow.kt:1-258)."""

    def __init__(self, stx: SignedTransaction):
        self.stx = stx

    def call(self):
        hub = self.service_hub
        our_keys = hub.key_management.keys
        notary_key = stx_notary_key = None
        if self.stx.notary is not None:
            notary_key = self.stx.notary.owning_key
        stx = self.stx
        for key in stx.tx.must_sign:
            if key == notary_key or any(k in our_keys for k in key.keys):
                continue
            # a signature already attached (e.g. an oracle's tear-off
            # signature collected before this flow) is not re-requested
            if key.is_fulfilled_by({s.by for s in stx.sigs}):
                continue
            party = _party_by_key(hub, key)
            if party is None:
                raise FlowException(
                    f"No well-known party found for signer {key.to_string_short()}")
            resp = yield SendAndReceive(party, SignTransactionRequest(stx),
                                        DigitalSignatureWithKey)

            def validate(sig, _key=key):
                sig.verify(stx.id.bytes)
                if not _key.is_fulfilled_by({sig.by}):
                    raise FlowException("Signature from an unexpected key")
                return sig

            stx = stx.plus(resp.unwrap(validate))
        return stx


def install_core_flows(smm) -> None:
    """Register the always-on service handlers every node must serve
    (AbstractNode.installCoreFlows, AbstractNode.kt:285)."""
    from .api import flow_name
    smm.register_flow_factory(flow_name(FetchTransactionsFlow),
                              FetchTransactionsHandler)
    smm.register_flow_factory(flow_name(FetchAttachmentsFlow),
                              FetchAttachmentsHandler)
    smm.register_flow_factory(flow_name(BroadcastTransactionFlow),
                              NotifyTransactionHandler)


class SignTransactionFlow(FlowLogic):
    """Counter-signer side (abstract in the reference; subclass and override
    `check_transaction` to add business validation)."""

    def __init__(self, peer):
        self.peer = peer

    def check_transaction(self, stx: SignedTransaction) -> None:
        """Override for business checks; raise FlowException to refuse."""

    def call(self):
        req = yield Receive(self.peer, SignTransactionRequest)
        stx = req.unwrap(lambda r: r.stx)
        # the initiator must already have signed it
        stx.check_signatures_are_valid()
        self.check_transaction(stx)
        hub = self.service_hub
        our_key = next((k for k in stx.tx.must_sign
                        for leaf in k.keys
                        if leaf in hub.key_management.keys), None)
        if our_key is None:
            raise FlowException("Transaction does not require our signature")
        leaf = next(k for k in our_key.keys if k in hub.key_management.keys)
        sig = hub.key_management.sign(stx.id.bytes, leaf)
        yield Send(self.peer, sig)
        return None
