"""State-replacement flows: notary change (and the acceptor protocol shape
contract upgrades share).

Reference parity: AbstractStateReplacementFlow + NotaryChangeFlow
(core/flows/AbstractStateReplacementFlow.kt, NotaryChangeFlow.kt): the
instigator builds a NotaryChange transaction (same state, new notary),
part-signs and sends the proposal to every other participant; each acceptor
verifies the proposal really is a pure notary change for a state it knows,
countersigns; the instigator notarises with the OLD notary (which releases
the states from its commit log domain) and finalises to everyone.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from ..core.contracts.structures import StateAndRef, StateRef, TransactionState
from ..core.contracts.transaction_types import TransactionType
from ..core.crypto.signatures import DigitalSignatureWithKey
from ..core.serialization import register_type
from ..core.transactions.signed import SignedTransaction
from ..core.transactions.wire import WireTransaction
from .api import (FlowException, FlowLogic, Receive, Send, SendAndReceive,
                  initiating_flow)
from .library import FinalityFlow, NotaryFlow, _party_by_key


@dataclass(frozen=True)
class ReplacementProposal:
    """stx: the part-signed replacement; ref: which state is being replaced."""

    stx: Any
    ref: Any        # StateRef


register_type("flows.ReplacementProposal", ReplacementProposal)


class StateReplacementException(FlowException):
    pass


@initiating_flow
class NotaryChangeFlow(FlowLogic):
    """Instigator side (NotaryChangeFlow.Instigator)."""

    def __init__(self, state_and_ref: StateAndRef, new_notary):
        self.state_and_ref = state_and_ref
        self.new_notary = new_notary

    def call(self):
        hub = self.service_hub
        me = hub.my_info.legal_identity
        old_state = self.state_and_ref.state
        if old_state.notary == self.new_notary:
            raise StateReplacementException(
                "The new notary is the same as the current one")
        wtx = WireTransaction(
            inputs=(self.state_and_ref.ref,),
            outputs=(TransactionState(old_state.data, self.new_notary,
                                      old_state.encumbrance),),
            commands=(),
            notary=old_state.notary,
            must_sign=tuple(sorted(
                {getattr(p, "owning_key", p)
                 for p in old_state.data.participants}
                | {old_state.notary.owning_key})),
            type=TransactionType.NotaryChange)
        stx = hub.sign_initial_transaction(wtx)

        # collect acceptances from every OTHER participant
        our_keys = hub.key_management.keys
        for key in {getattr(p, "owning_key", p)
                    for p in old_state.data.participants}:
            if any(leaf in our_keys for leaf in key.keys):
                continue
            party = _party_by_key(hub, key)
            if party is None:
                raise StateReplacementException(
                    f"No well-known party for participant "
                    f"{key.to_string_short()}")
            resp = yield SendAndReceive(
                party, ReplacementProposal(stx, self.state_and_ref.ref),
                DigitalSignatureWithKey)

            def validate(sig, _key=key):
                sig.verify(stx.id.bytes)
                if not _key.is_fulfilled_by({sig.by}):
                    raise StateReplacementException(
                        "Acceptance signed by an unexpected key")
                return sig

            stx = stx.plus(resp.unwrap(validate))

        # FinalityFlow notarises with the OLD notary, records and broadcasts
        # (one consensus round — the reference Instigator does the same)
        participants = [
            p for p in (_party_by_key(hub, getattr(q, "owning_key", q))
                        for q in old_state.data.participants) if p is not None]
        final = yield from self.sub_flow(FinalityFlow(stx, participants))
        return StateAndRef(final.tx.outputs[0], StateRef(final.id, 0))


class NotaryChangeAcceptor(FlowLogic):
    """Acceptor side (AbstractStateReplacementFlow.Acceptor): verify the
    proposal is a pure notary change of a state we recognise, then sign."""

    def __init__(self, peer):
        self.peer = peer

    def call(self):
        req = yield Receive(self.peer, ReplacementProposal)
        proposal = req.unwrap(
            lambda r: r if isinstance(r, ReplacementProposal) else _refuse())
        stx: SignedTransaction = proposal.stx
        wtx = stx.tx
        if wtx.type != TransactionType.NotaryChange:
            raise StateReplacementException(
                "Proposal is not a notary-change transaction")
        if len(wtx.inputs) != 1 or len(wtx.outputs) != 1:
            raise StateReplacementException(
                "Notary change must replace exactly one state")
        if wtx.inputs[0] != proposal.ref:
            raise StateReplacementException("Proposal input mismatch")
        # the state's DATA must be untouched; only the notary moves
        hub = self.service_hub
        known = hub.load_state(proposal.ref)
        if known is None:
            raise StateReplacementException(
                "We do not know the state being replaced")
        if wtx.outputs[0].data != known.data:
            raise StateReplacementException(
                "Proposal alters the state, not just the notary")
        if wtx.outputs[0].notary == known.notary:
            raise StateReplacementException("Notary did not change")
        stx.check_signatures_are_valid()
        our_key = next(
            (leaf for k in wtx.must_sign for leaf in k.keys
             if leaf in hub.key_management.keys), None)
        if our_key is None:
            raise StateReplacementException(
                "Proposal does not require our signature")
        sig = hub.key_management.sign(stx.id.bytes, our_key)
        yield Send(self.peer, sig)
        return None


def _refuse():
    raise StateReplacementException("Malformed replacement proposal")


def install_notary_change_acceptor(smm) -> None:
    """Register the acceptor (nodes opt in, as with other core handlers)."""
    from .api import flow_name
    smm.register_flow_factory(flow_name(NotaryChangeFlow),
                              NotaryChangeAcceptor)
