"""The canonical binary codec.

Wire model: every value is transformed into a *wire tree* of msgpack-safe primitives
(None, bool, int64, bytes, str, list) plus tagged ExtType wrappers for everything
else, then packed with msgpack (C implementation) in one pass:

- ``ExtType(1, …)``  OBJ     — registered type: packb([type_name, [field wires…]])
- ``ExtType(2, …)``  MAP     — dict: packb([[k, v]…]) sorted by packed key bytes
- ``ExtType(3, …)``  SET     — set/frozenset: packb([…]) sorted by packed bytes
- ``ExtType(4, …)``  BIGINT  — arbitrary-precision int: sign byte + magnitude
- ``ExtType(5, …)``  ENUM    — packb([enum_type_name, member_name])

Registered types declare their wire fields; deserialization only ever constructs
registered types (whitelist enforcement).
"""
from __future__ import annotations

import dataclasses
import datetime
import enum
from typing import Any, Callable

import msgpack

from ..crypto.secure_hash import SecureHash

FORMAT_VERSION = 1
_MAGIC = b"\xc0\x9d\xa1" + bytes([FORMAT_VERSION])  # leads every top-level message

_EXT_OBJ = 1
_EXT_MAP = 2
_EXT_SET = 3
_EXT_BIGINT = 4
_EXT_ENUM = 5
_EXT_INSTANT = 6  # UTC datetime as epoch-microseconds (big-endian i64)
_EXT_OBJ_SCHEMA = 7  # [name, [field names], fields] — carpentable object

_I64_MIN, _I64_MAX = -(2**63), 2**63 - 1


class SerializationError(Exception):
    pass


_EPOCH = datetime.datetime(1970, 1, 1, tzinfo=datetime.timezone.utc)


def exact_epoch_micros(t: datetime.datetime) -> int:
    """Exact integer epoch-microseconds (no float path — ``timestamp()`` truncation
    corrupts ~1% of microsecond values, which would fork consensus hashes)."""
    if t.tzinfo is None:
        t = t.replace(tzinfo=datetime.timezone.utc)
    return (t - _EPOCH) // datetime.timedelta(microseconds=1)


# ---------------------------------------------------------------------------
# Type registry (the whitelist)
# ---------------------------------------------------------------------------

# name -> (cls, to_fields, from_fields)
_REGISTRY: dict[str, tuple[type, Callable, Callable]] = {}
_BY_CLASS: dict[type, str] = {}
_ENUM_REGISTRY: dict[str, type] = {}
# schema-carrying types (name -> field names); their wire form embeds the
# field names so receivers WITHOUT the class can still materialize them
_SCHEMA_NAMES: dict[str, list[str]] = {}
# receiver-side synthesized classes for unknown schema'd names
# (ClassCarpenter.kt:30-447 analog) — deliberately NOT in _REGISTRY: the
# trusted whitelist stays authoritative, and a later real registration of
# the same name simply wins for subsequent decodes
_CARPENTED: dict[str, tuple[type, list[str]]] = {}
_CARPENTED_BY_CLASS: dict[type, str] = {}


def register_type(name: str, cls: type,
                  to_fields: Callable[[Any], list] | None = None,
                  from_fields: Callable[[list], Any] | None = None,
                  carry_schema: bool = False) -> None:
    """Register a type for serialization. Defaults handle dataclasses (fields in
    declaration order — deterministic).

    ``carry_schema=True`` writes the field NAMES onto the wire so a receiver
    that does not know the class can carpent a property-bag stand-in
    (see :func:`carpented_class`) — use it for types expected to travel to
    nodes without the defining CorDapp module."""
    if name in _REGISTRY and _REGISTRY[name][0] is not cls:
        raise SerializationError(f"Serialization name collision: {name!r}")
    if carry_schema and (to_fields is not None or from_fields is not None):
        # the carried names are the dataclass's declared fields; a custom
        # codec could reorder/transform values, silently binding receivers'
        # carpented attributes to the wrong values
        raise SerializationError(
            "carry_schema requires the default dataclass field codec")
    if to_fields is None or from_fields is None or carry_schema:
        if not dataclasses.is_dataclass(cls):
            raise SerializationError(
                f"{cls!r} is not a dataclass; provide to_fields/from_fields"
                + (" (carry_schema needs dataclass field names)"
                   if carry_schema else ""))
        field_names = [f.name for f in dataclasses.fields(cls)]
        to_fields = to_fields or (lambda obj, _fn=field_names:
                                  [getattr(obj, n) for n in _fn])
        # Sequences decode as lists; dataclass wire types are immutable, so coerce
        # top-level list fields back to tuples for equality/hashability.
        from_fields = from_fields or (
            lambda fields, _c=cls: _c(*[tuple(f) if isinstance(f, list) else f
                                        for f in fields]))
        if carry_schema:
            _SCHEMA_NAMES[name] = field_names
    _REGISTRY[name] = (cls, to_fields, from_fields)
    _BY_CLASS[cls] = name


#: Cap on distinct carpented names: classes are heavyweight and live
#: instances pin them, so eviction would fork a name across two classes —
#: refuse instead (no legitimate peer set ships thousands of state types).
_CARPENTED_MAX = 4096
#: Cap on fields per carpented schema: make_dataclass execs a class body
#: sized by the field count, and carpented classes are pinned for the
#: process lifetime — an unbounded count is a wire-reachable memory/CPU
#: sink. No legitimate state type approaches this.
_CARPENTED_MAX_FIELDS = 256


def carpented_class(name: str, field_names: list[str]) -> type:
    """Synthesize (once per name+schema) a frozen-dataclass property bag for
    a schema'd wire object whose real class is absent — the runtime class
    synthesis of the reference's ClassCarpenter, minus bytecode: the bag is
    inert data (no methods), so the deserialization whitelist's gadget
    protection is preserved.

    SCHEMA EVOLUTION: a second schema under the same name carpents the
    UNION of all fields seen so far (stable order: first-seen first) and
    becomes the name's class for subsequent decodes — every field defaults
    to None, so a wire form carrying any subset still materializes
    (reference evolution direction: ClassCarpenter.kt:30-447 +
    amqp/SerializerFactory.kt).  Each carpented CLASS remembers its own
    schema (``__corda_carpented_fields__``): instances re-serialize under
    the schema they were built with — a bag decoded before an evolution
    stays bit-exact on re-serialization; a union bag re-serializes under
    the union schema.  Unions grow monotonically and the per-schema field
    cap bounds them, so a hostile peer cannot mint unbounded classes for
    one name.  Every hostile-input failure mode is a SerializationError."""
    entry = _CARPENTED.get(name)
    if entry is not None:
        cls, known = entry
        if known == list(field_names):
            return cls
        union = list(known) + [fn for fn in field_names if fn not in known]
        if union == known:        # subset of what we already know
            return cls
        return _carpent(name, union)
    return _carpent(name, list(field_names))


#: Total class syntheses (first carpents AND union evolutions): every
#: synthesized class is pinned for the process lifetime, so the budget
#: must count evolutions too — otherwise a hostile peer could stream
#: one-field-at-a-time schema changes and mint ~256 classes per name
#: beyond the name cap.
_carpent_count = 0


def _carpent(name: str, field_names: list[str]) -> type:
    import keyword

    global _carpent_count
    if _carpent_count >= _CARPENTED_MAX:
        raise SerializationError(
            f"Carpented-class budget ({_CARPENTED_MAX}) exhausted; "
            f"refusing to synthesize {name!r}")
    if not isinstance(name, str) or not name:
        raise SerializationError(f"Bad carpented type name {name!r}")
    if len(field_names) > _CARPENTED_MAX_FIELDS:
        raise SerializationError(
            f"Carpented schema for {name!r} has {len(field_names)} fields "
            f"(limit {_CARPENTED_MAX_FIELDS})")
    seen = set()
    for fn in field_names:
        if (not isinstance(fn, str) or not fn.isidentifier()
                or fn.startswith("__") or keyword.iskeyword(fn)
                or fn in seen):
            raise SerializationError(f"Bad carpented field name {fn!r}")
        seen.add(fn)
    try:
        cls = dataclasses.make_dataclass(
            name.rsplit(".", 1)[-1] or "Carpented",
            [(fn, Any, dataclasses.field(default=None))
             for fn in field_names],
            frozen=True, eq=True)
    except (TypeError, ValueError) as e:
        raise SerializationError(
            f"Cannot carpent {name!r}: {e}") from e
    cls.__corda_carpented__ = name
    cls.__corda_carpented_fields__ = list(field_names)
    _CARPENTED[name] = (cls, list(field_names))
    _CARPENTED_BY_CLASS[cls] = name
    _carpent_count += 1
    return cls


def serializable(name: str | None = None,
                 to_fields: Callable | None = None,
                 from_fields: Callable | None = None):
    """Class decorator: ``@serializable()`` registers the class under its qualname."""
    def wrap(cls):
        reg_name = name or cls.__name__
        if issubclass(cls, enum.Enum):
            _ENUM_REGISTRY[reg_name] = cls
            cls.__corda_enum_name__ = reg_name
        else:
            register_type(reg_name, cls, to_fields, from_fields)
        return cls
    return wrap


def registered_name(cls: type) -> str | None:
    return _BY_CLASS.get(cls)


# ---------------------------------------------------------------------------
# Wire-tree transform
# ---------------------------------------------------------------------------

def _packb(wire) -> bytes:
    return msgpack.packb(wire, use_bin_type=True, strict_types=True)


def to_wire(obj: Any) -> Any:
    if obj is None or isinstance(obj, (bool, str)):
        return obj
    if isinstance(obj, int) and not isinstance(obj, bool):
        if _I64_MIN <= obj <= _I64_MAX:
            return obj
        sign = 1 if obj >= 0 else 0
        mag = abs(obj)
        return msgpack.ExtType(_EXT_BIGINT, bytes([sign]) +
                               mag.to_bytes((mag.bit_length() + 7) // 8 or 1, "big"))
    if isinstance(obj, (bytes, bytearray, memoryview)):
        return bytes(obj)
    if isinstance(obj, float):
        raise SerializationError(
            "Floats are not permitted in consensus data (non-deterministic); "
            "use integer quantities (Amount semantics)")
    if isinstance(obj, (list, tuple)):
        return [to_wire(x) for x in obj]
    if isinstance(obj, dict):
        pairs = sorted(([_packb(to_wire(k)), to_wire(v)] for k, v in obj.items()),
                       key=lambda kv: kv[0])
        return msgpack.ExtType(_EXT_MAP, _packb(pairs))
    if isinstance(obj, (set, frozenset)):
        elems = sorted(_packb(to_wire(x)) for x in obj)
        return msgpack.ExtType(_EXT_SET, _packb(elems))
    if isinstance(obj, datetime.datetime):
        return msgpack.ExtType(_EXT_INSTANT,
                               exact_epoch_micros(obj).to_bytes(8, "big", signed=True))
    if isinstance(obj, enum.Enum):
        ename = getattr(type(obj), "__corda_enum_name__", None)
        if ename is None:
            raise SerializationError(f"Enum {type(obj)!r} is not @serializable")
        return msgpack.ExtType(_EXT_ENUM, _packb([ename, obj.name]))
    name = _BY_CLASS.get(type(obj))
    if name is None:
        cname = _CARPENTED_BY_CLASS.get(type(obj))
        if cname is not None:
            # carpented bag: re-serializes under ITS OWN schema (the one
            # its class was built with), so pre-evolution instances stay
            # bit-exact and union bags emit the union schema
            field_names = type(obj).__corda_carpented_fields__
            fields = [to_wire(getattr(obj, fn)) for fn in field_names]
            return msgpack.ExtType(_EXT_OBJ_SCHEMA,
                                   _packb([cname, field_names, fields]))
        raise SerializationError(
            f"Type {type(obj).__module__}.{type(obj).__qualname__} is not registered "
            f"for serialization (whitelist violation)")
    _, to_fields, _ = _REGISTRY[name]
    fields = [to_wire(f) for f in to_fields(obj)]
    schema = _SCHEMA_NAMES.get(name)
    if schema is not None:
        return msgpack.ExtType(_EXT_OBJ_SCHEMA, _packb([name, schema, fields]))
    return msgpack.ExtType(_EXT_OBJ, _packb([name, fields]))


def _unpackb(data: bytes):
    return msgpack.unpackb(data, raw=False, strict_map_key=False,
                           ext_hook=lambda c, d: msgpack.ExtType(c, d))


def from_wire(wire: Any) -> Any:
    if wire is None or isinstance(wire, (bool, int, str, bytes)):
        return wire
    # NB: ExtType subclasses tuple, so it must be checked before the sequence case.
    if isinstance(wire, msgpack.ExtType):
        code, data = wire.code, wire.data
        if code == _EXT_BIGINT:
            if len(data) < 2:
                raise SerializationError("Truncated bigint")
            val = int.from_bytes(data[1:], "big")
            return val if data[0] else -val
        if code == _EXT_MAP:
            return {_freeze(from_wire(_unpackb(k))): from_wire(v)
                    for k, v in _unpackb(data)}
        if code == _EXT_SET:
            return frozenset(_freeze(from_wire(_unpackb(e))) for e in _unpackb(data))
        if code == _EXT_INSTANT:
            micros = int.from_bytes(data, "big", signed=True)
            return datetime.datetime.fromtimestamp(micros / 1_000_000,
                                                   tz=datetime.timezone.utc)
        if code == _EXT_ENUM:
            ename, member = _unpackb(data)
            cls = _ENUM_REGISTRY.get(ename)
            if cls is None:
                raise SerializationError(f"Enum {ename!r} is not whitelisted")
            return cls[member]
        if code == _EXT_OBJ:
            name, fields = _unpackb(data)
            entry = _REGISTRY.get(name)
            if entry is None:
                raise SerializationError(f"Type {name!r} is not whitelisted")
            _, _, from_fields = entry
            return from_fields([from_wire(f) for f in fields])
        if code == _EXT_OBJ_SCHEMA:
            name, field_names, fields = _unpackb(data)
            if len(field_names) != len(fields):
                raise SerializationError(
                    f"Schema'd object {name!r}: {len(field_names)} names "
                    f"vs {len(fields)} fields")
            if len(set(field_names)) != len(field_names):
                # a duplicated name is always hostile/corrupt wire: binding
                # would silently keep only the last value (dict semantics in
                # both the by-name rebind and the carpenter kwargs)
                seen: set = set()
                dupes = sorted({fn for fn in field_names
                                if fn in seen or seen.add(fn)})
                raise SerializationError(
                    f"Schema'd object {name!r}: duplicate field names "
                    f"{dupes}")
            entry = _REGISTRY.get(name)
            if entry is not None:       # the real class is known: it wins
                cls, _, from_fields = entry
                # Bind by NAME against the local declaration, never by wire
                # position: a peer whose version declares fields in a
                # different order (schema skew) must not silently bind
                # values to the wrong attributes.
                local = _SCHEMA_NAMES.get(name)
                if local is None and dataclasses.is_dataclass(cls):
                    local = [f.name for f in dataclasses.fields(cls)]
                if local is not None and list(field_names) != local:
                    if sorted(field_names) == sorted(local):
                        by_name = dict(zip(field_names, fields))
                        fields = [by_name[n] for n in local]
                    elif name in _SCHEMA_NAMES:
                        # SCHEMA EVOLUTION (reference ClassCarpenter.kt +
                        # amqp/SerializerFactory.kt evolution direction):
                        # a peer on another VERSION of the type — fields
                        # it doesn't carry fill from local dataclass
                        # defaults; fields the local version dropped are
                        # ignored. Only carry_schema types qualify (their
                        # codec is the default dataclass one, so binding
                        # by declaration order is sound); no default for
                        # a missing field ⇒ genuinely incompatible.
                        return _evolved_decode(name, cls, local,
                                               field_names, fields)
                    else:
                        raise SerializationError(
                            f"Schema'd object {name!r}: carried fields "
                            f"{sorted(field_names)} do not match local "
                            f"declaration {sorted(local)}")
                try:
                    return from_fields([from_wire(f) for f in fields])
                except TypeError as e:
                    raise SerializationError(
                        f"Schema'd object {name!r} does not fit local "
                        f"class: {e}") from e
            cls = carpented_class(name, field_names)
            return cls(**{fn: _freeze(from_wire(f))
                          for fn, f in zip(field_names, fields)})
        raise SerializationError(f"Unknown ext code {code}")
    if isinstance(wire, (list, tuple)):
        return [from_wire(x) for x in wire]
    raise SerializationError(f"Unexpected wire value of type {type(wire)!r}")


def _freeze(v):
    return tuple(v) if isinstance(v, list) else v


def _evolved_decode(name: str, cls, local: list[str], field_names, fields):
    """Decode a schema'd object whose carried field set differs from the
    local version of the class: carried-and-local fields bind by name,
    locally-ADDED fields take the dataclass default (the v1→v2 direction),
    carried-but-REMOVED fields are dropped (v2→v1).  A locally-added field
    WITHOUT a default is a genuine incompatibility and fails typed."""
    by_name = {fn: from_wire(v) for fn, v in zip(field_names, fields)}
    spec = {f.name: f for f in dataclasses.fields(cls)}
    vals = []
    for n in local:
        if n in by_name:
            vals.append(_freeze(by_name[n]))
            continue
        f = spec[n]
        # defaults freeze like carried values do (a list default becomes a
        # tuple): evolved instances must hash/compare like native ones
        if f.default is not dataclasses.MISSING:
            vals.append(_freeze(f.default))
        elif f.default_factory is not dataclasses.MISSING:
            vals.append(_freeze(f.default_factory()))
        else:
            raise SerializationError(
                f"Schema'd object {name!r}: peer version lacks field "
                f"{n!r} and the local class declares no default for it")
    try:
        return cls(*vals)
    except TypeError as e:
        raise SerializationError(
            f"Schema'd object {name!r} does not fit local class: {e}"
        ) from e


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

def serialize(obj: Any) -> bytes:
    return _MAGIC + _packb(to_wire(obj))


def deserialize(data: bytes) -> Any:
    if len(data) < 4 or data[:3] != _MAGIC[:3]:
        raise SerializationError("Bad magic: not corda_tpu canonical bytes")
    if data[3] != FORMAT_VERSION:
        raise SerializationError(f"Unsupported format version {data[3]}")
    try:
        return from_wire(_unpackb(data[4:]))
    except SerializationError:
        raise
    except Exception as e:
        # Untrusted wire bytes must always fail typed, never leak raw decode errors.
        raise SerializationError(f"Malformed canonical bytes: {type(e).__name__}: {e}") from e


def serialized_hash(obj: Any) -> SecureHash:
    """Merkle component leaf hash: SHA-256 of the canonical bytes (magic included,
    so leaves are domain-separated from raw user bytes)."""
    return SecureHash.sha256(serialize(obj))
