"""Deterministic canonical serialization — the wire, checkpoint and Merkle-leaf codec.

Replaces the reference's Kryo stack (core/.../serialization/Kryo.kt — which the
reference itself flags as a placeholder wire format). Design goals, in order:

1. **Deterministic**: one object graph → exactly one byte string (sorted maps/sets,
   canonical int widths, no object references/backrefs). Merkle leaf hashes are
   SHA-256 of these bytes (``serialized_hash`` — MerkleTransaction.kt:16-18 coupling),
   so determinism is consensus-critical.
2. **Whitelisted**: only registered types deserialize (CordaClassResolver.kt:1-225
   security model) — attacker-supplied bytes can never construct arbitrary objects.
3. **Versioned**: a one-byte format version leads every top-level message.
"""
from .codec import (
    serializable, serialize, deserialize, serialized_hash, to_wire, from_wire,
    SerializationError, register_type, registered_name,
)
from . import builtin_types as _builtin_types  # noqa: F401  (whitelist side effects)

__all__ = [
    "serializable", "serialize", "deserialize", "serialized_hash",
    "to_wire", "from_wire", "SerializationError", "register_type", "registered_name",
]
