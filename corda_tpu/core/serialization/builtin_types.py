"""Whitelist registrations for the crypto-layer primitives.

(DefaultWhitelist.kt analog — the types every wire message may contain.)
"""
from __future__ import annotations

from . import codec
from ..crypto.secure_hash import SecureHash
from ..crypto.keys import PublicKey
from ..crypto.composite import CompositeKey
from ..crypto.schemes import scheme_by_id, COMPOSITE_KEY
from ..crypto.signatures import DigitalSignature, DigitalSignatureWithKey


def _pubkey_to_fields(key: PublicKey) -> list:
    return [key.scheme.scheme_number_id, key.encoded]


def _pubkey_from_fields(fields: list) -> PublicKey:
    sid, encoded = fields
    if sid == COMPOSITE_KEY.scheme_number_id:
        return CompositeKey.decode(encoded)
    return PublicKey(scheme_by_id(sid), encoded)


codec.register_type("SecureHash", SecureHash,
                    to_fields=lambda h: [h.bytes],
                    from_fields=lambda f: SecureHash(f[0]))
codec.register_type("PublicKey", PublicKey, _pubkey_to_fields, _pubkey_from_fields)
# CompositeKey shares the PublicKey wire shape (scheme id distinguishes them).
codec._BY_CLASS[CompositeKey] = "PublicKey"
codec.register_type("DigitalSignature", DigitalSignature,
                    to_fields=lambda s: [s.bytes],
                    from_fields=lambda f: DigitalSignature(f[0]))
codec.register_type("DigitalSignature.WithKey", DigitalSignatureWithKey,
                    to_fields=lambda s: [s.bytes, s.by],
                    from_fields=lambda f: DigitalSignatureWithKey(f[0], f[1]))
