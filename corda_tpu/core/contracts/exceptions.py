"""Transaction verification/resolution exception taxonomy.

Reference parity: core/.../contracts/TransactionVerification.kt:100-128.
Every exception carries the offending transaction id so failures are attributable
across the async verifier boundary.
"""
from __future__ import annotations

from ..crypto.secure_hash import SecureHash


class FlowException(Exception):
    """Base for errors that propagate across flow sessions to the counterparty
    (reference: core/.../flows/FlowException.kt)."""


class TransactionVerificationException(FlowException):
    def __init__(self, tx_id: SecureHash, message: str):
        super().__init__(f"{message}, transaction: {tx_id}")
        self.tx_id = tx_id


class ContractRejection(TransactionVerificationException):
    def __init__(self, tx_id: SecureHash, contract, cause: Exception):
        super().__init__(tx_id, f"Contract verification failed: {cause}, contract: {contract}")
        self.contract = contract
        self.cause = cause


class MoreThanOneNotary(TransactionVerificationException):
    def __init__(self, tx_id: SecureHash):
        super().__init__(tx_id, "More than one notary")


class SignersMissing(TransactionVerificationException):
    def __init__(self, tx_id: SecureHash, missing: list):
        super().__init__(tx_id, f"Signers missing: {', '.join(str(m) for m in missing)}")
        self.missing = missing


class DuplicateInputStates(TransactionVerificationException):
    def __init__(self, tx_id: SecureHash, duplicates: set):
        super().__init__(tx_id, f"Duplicate inputs: {', '.join(str(d) for d in duplicates)}")
        self.duplicates = duplicates


class InvalidNotaryChange(TransactionVerificationException):
    def __init__(self, tx_id: SecureHash):
        super().__init__(tx_id, "Detected a notary change. Outputs must use the same notary as inputs")


class NotaryChangeInWrongTransactionType(TransactionVerificationException):
    def __init__(self, tx_id: SecureHash, tx_notary, output_notary):
        super().__init__(tx_id, f"Found unexpected notary change in transaction. "
                                f"Tx notary: {tx_notary}, found: {output_notary}")


class TransactionMissingEncumbranceException(TransactionVerificationException):
    INPUT = "input"
    OUTPUT = "output"

    def __init__(self, tx_id: SecureHash, missing: int, in_out: str):
        super().__init__(tx_id, f"Missing required encumbrance {missing} in {in_out}")


class TransactionResolutionException(FlowException):
    def __init__(self, hash_not_found: SecureHash):
        super().__init__(f"Transaction resolution failure for {hash_not_found}")
        self.hash = hash_not_found


class AttachmentResolutionException(FlowException):
    def __init__(self, hash_not_found: SecureHash):
        super().__init__(f"Attachment resolution failure for {hash_not_found}")
        self.hash = hash_not_found
