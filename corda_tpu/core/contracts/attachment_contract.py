"""Attachment-delivered contract code, executed in the deterministic sandbox.

Reference parity (VERDICT r2 #5):
- ``AttachmentsClassLoader.kt``: during verification, contract classes load
  from the transaction's attachment jars — a peer can verify a contract it
  never installed, because the code travels WITH the transaction.
- ``experimental/sandbox WhitelistClassLoader.java:1-356``: that loaded code
  runs gated — whitelisted constructs only, runtime cost accounting.

The TPU-native form: contract verify logic ships as PYTHON SOURCE in a
content-addressed attachment. ``SandboxedState`` carries (attachment id,
contract class name, plain-data fields); its contract resolves the source
from the transaction's own resolved attachments, validates it against the
deterministic whitelist, and runs ``verify`` under the instruction budget
(core.contracts.sandbox). A hostile attachment — banned constructs, budget
exhaustion, or a verify that rejects — fails verification like any contract
violation; it can never run unconfined.

The state's FIELDS are codec-plain (tuples of (name, value) pairs), so a
peer deserializes the state without any contract-specific Python types
installed — the wire-format half of the classloader story.
"""
from __future__ import annotations

from dataclasses import dataclass

from ..crypto.secure_hash import SecureHash
from ..serialization import register_type
from .exceptions import TransactionVerificationException
from .sandbox import (DeterministicSandbox, SandboxBudgetError,
                      SandboxViolation)
from .structures import CommandData, Contract, ContractState


@dataclass(frozen=True)
class SandboxedCommand(CommandData):
    """A command for attachment-delivered contracts: a verb name + plain
    arguments (the sandboxed code dispatches on the name)."""

    name: str
    args: tuple = ()


@dataclass(frozen=True)
class SandboxedState(ContractState):
    """A state whose contract logic lives in ``attachment_id``.

    ``fields`` is a tuple of (name, value) pairs of codec-plain values —
    deserializable by ANY peer, no contract module required."""

    attachment_id: SecureHash
    contract_class: str
    fields: tuple                 # ((name, value), ...)
    owners: tuple                 # participant PublicKeys

    @property
    def contract(self) -> "AttachmentContract":
        return AttachmentContract(self.attachment_id, self.contract_class)

    @property
    def participants(self):
        return list(self.owners)

    def field(self, name: str):
        for key, value in self.fields:
            if key == name:
                return value
        raise KeyError(name)


register_type("sandbox.SandboxedCommand", SandboxedCommand)
register_type("sandbox.SandboxedState", SandboxedState)

#: Budget for one sandboxed contract verification (statements + iterations).
VERIFY_BUDGET = 200_000


@dataclass(frozen=True)
class AttachmentContract(Contract):
    """The classloader seam: verify() finds the source in the transaction's
    resolved attachments and runs it sandboxed. Equality by (attachment,
    class) so the platform's one-verify-per-contract dispatch dedupes."""

    attachment_id: SecureHash
    contract_class: str

    def verify(self, tx) -> None:
        attachment = next(
            (a for a in tx.attachments if a.id == self.attachment_id), None)
        if attachment is None:
            raise TransactionVerificationException(
                tx.id, f"contract attachment {self.attachment_id} is not "
                       f"attached to the transaction")
        try:
            source = attachment.data.decode("utf-8")
        except UnicodeDecodeError as e:
            raise TransactionVerificationException(
                tx.id, f"contract attachment is not source text: {e}")
        sandbox = DeterministicSandbox(instruction_budget=VERIFY_BUDGET)
        try:
            namespace = sandbox.load(source)
        except SandboxViolation as e:
            raise TransactionVerificationException(
                tx.id, f"contract attachment rejected by the sandbox: {e}")
        except SandboxBudgetError as e:
            raise TransactionVerificationException(
                tx.id, f"contract attachment exhausted its budget at "
                       f"load: {e}")
        contract_cls = namespace.get(self.contract_class)
        if contract_cls is None:
            raise TransactionVerificationException(
                tx.id, f"attachment does not define contract class "
                       f"{self.contract_class!r}")
        view = _transaction_view(self, tx)
        try:
            sandbox.run(contract_cls().verify, view)
        except SandboxBudgetError as e:
            raise TransactionVerificationException(
                tx.id, f"sandboxed contract exhausted its budget: {e}")
        except TransactionVerificationException:
            raise
        except Exception as e:
            raise TransactionVerificationException(
                tx.id, f"sandboxed contract rejected: {e}")


def _transaction_view(contract: AttachmentContract, tx) -> dict:
    """Reduce the transaction to plain data for the sandboxed verify: only
    the states/commands belonging to THIS contract, as dicts of primitives
    (the sandbox whitelist has no framework types)."""

    def state_view(state):
        return {"class": state.contract_class,
                "fields": dict(state.fields),
                "owners": [k.encoded for k in state.owners]}

    inputs = [state_view(s) for s in tx.inputs
              if isinstance(s, SandboxedState)
              and s.contract == contract]
    outputs = [state_view(s) for s in tx.outputs
               if isinstance(s, SandboxedState)
               and s.contract == contract]
    commands = [{"name": c.value.name, "args": list(c.value.args),
                 "signers": [k.encoded for k in c.signers]}
                for c in tx.commands
                if isinstance(c.value, SandboxedCommand)]
    return {"inputs": inputs, "outputs": outputs, "commands": commands}
