"""Integer token-quantity arithmetic.

Reference parity: core/.../contracts/Amount.kt:1-442 — quantities are integer counts
of the smallest token unit (pennies, cents); mixing tokens throws; negative amounts
throw. Floats never appear (consensus determinism).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Iterable

from ..serialization import serializable


@serializable("Currency")
@dataclass(frozen=True, order=True)
class Currency:
    """ISO-4217-style currency token (the reference uses java.util.Currency)."""

    code: str
    default_fraction_digits: int = 2

    def __str__(self):
        return self.code


USD = Currency("USD")
GBP = Currency("GBP")
EUR = Currency("EUR")
CHF = Currency("CHF")
_WELL_KNOWN = {c.code: c for c in (USD, GBP, EUR, CHF)}


def currency(code: str) -> Currency:
    return _WELL_KNOWN.get(code, Currency(code))


@serializable("Amount")
@dataclass(frozen=True)
class Amount:
    """``quantity`` of the smallest unit of ``token`` (token may be a Currency or an
    ``Issued`` wrapper — Amount[Issued[Currency]] is issued cash)."""

    quantity: int
    token: Any

    def __post_init__(self):
        if not isinstance(self.quantity, int) or isinstance(self.quantity, bool):
            raise ValueError("Amount quantity must be an int")
        if self.quantity < 0:
            raise ValueError("Negative amounts are not allowed")

    @staticmethod
    def from_decimal(value, token) -> "Amount":
        digits = _fraction_digits(token)
        q = round(value * (10 ** digits))
        return Amount(int(q), token)

    def to_decimal(self) -> float:
        return self.quantity / (10 ** _fraction_digits(self.token))

    def _check_token(self, other: "Amount"):
        if self.token != other.token:
            raise ValueError(f"Token mismatch: {self.token} vs {other.token}")

    def __add__(self, other: "Amount") -> "Amount":
        self._check_token(other)
        return Amount(self.quantity + other.quantity, self.token)

    def __sub__(self, other: "Amount") -> "Amount":
        self._check_token(other)
        return Amount(self.quantity - other.quantity, self.token)

    def __mul__(self, factor: int) -> "Amount":
        if not isinstance(factor, int):
            raise ValueError("Amounts may only be multiplied by ints")
        return Amount(self.quantity * factor, self.token)

    __rmul__ = __mul__

    def __lt__(self, other: "Amount") -> bool:
        self._check_token(other)
        return self.quantity < other.quantity

    def __le__(self, other: "Amount") -> bool:
        self._check_token(other)
        return self.quantity <= other.quantity

    def __gt__(self, other):
        return not self.__le__(other)

    def __ge__(self, other):
        return not self.__lt__(other)

    def splits(self, partitions: int) -> list["Amount"]:
        """Split as evenly as possible into ``partitions`` amounts that sum exactly."""
        base, rem = divmod(self.quantity, partitions)
        return [Amount(base + (1 if i < rem else 0), self.token)
                for i in range(partitions)]

    def __str__(self):
        return f"{self.to_decimal():.2f} {_token_str(self.token)}"


def _fraction_digits(token) -> int:
    if isinstance(token, Currency):
        return token.default_fraction_digits
    inner = getattr(token, "product", None)
    if isinstance(inner, Currency):
        return inner.default_fraction_digits
    return 0


def _token_str(token) -> str:
    return str(token)


def sum_or_none(amounts: Iterable[Amount]) -> Amount | None:
    total = None
    for a in amounts:
        total = a if total is None else total + a
    return total


def sum_or_throw(amounts: Iterable[Amount]) -> Amount:
    total = sum_or_none(amounts)
    if total is None:
        raise ValueError("Cannot sum an empty list of amounts")
    return total


def sum_or_zero(amounts: Iterable[Amount], token) -> Amount:
    return sum_or_none(amounts) or Amount(0, token)
