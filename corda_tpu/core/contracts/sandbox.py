"""Deterministic sandbox for contract verification code.

Reference parity: experimental/sandbox — the prototype deterministic JVM
sandbox for contract code (WhitelistClassLoader.java:1-356: whitelist class
loading + ASM bytecode rewriting; visitors/CostInstrumentingMethodVisitor +
costing/RuntimeCostAccounter: runtime cost accounting that kills runaway
code). The TPU build's contract bodies are Python, so the same two defenses
become:

- **Whitelist validation** (the WhitelistClassLoader role): contract source
  is parsed to an AST and rejected unless every construct is on the
  whitelist — no imports outside the allowed set, no dunder/underscore
  attribute access, no global/nonlocal, no async, no set displays (string
  hashing is process-seeded, so set iteration order is nondeterministic),
  and execution sees only a curated builtins table (no eval/exec/open/
  getattr/globals/hash/id/print...).
- **Cost accounting** (the CostInstrumentingMethodVisitor role): the AST is
  rewritten before compilation so every statement charges the instruction
  budget and every loop/comprehension iterates through a charging iterator;
  exhausting the budget raises SandboxCostExceeded mid-flight, exactly like
  the reference's TerminateException on runtime-cost thresholds.

Determinism, not security isolation, is the goal (same stance as the
reference prototype): the sandbox guarantees that a contract either
produces the same verdict on every node or dies the same way on every node.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass


class SandboxViolation(Exception):
    """Contract source uses a construct outside the deterministic whitelist."""


class SandboxCostExceeded(Exception):
    """Contract execution exhausted its instruction budget."""


_SAFE_BUILTIN_NAMES = (
    "abs", "all", "any", "bool", "bytes", "chr", "dict", "divmod",
    "enumerate", "filter", "float", "format", "int", "isinstance",
    "issubclass", "iter", "len", "list", "map", "max", "min", "next", "ord",
    "pow", "range", "repr", "reversed", "round", "slice", "sorted", "str",
    "sum", "tuple", "zip",
    # exceptions contract code may raise/catch
    "Exception", "ValueError", "TypeError", "ArithmeticError",
    "AssertionError", "ZeroDivisionError", "StopIteration", "IndexError",
    "KeyError",
)

_BANNED_NODES = {
    ast.Import: "import",
    ast.ImportFrom: "import",
    ast.Global: "global",
    ast.Nonlocal: "nonlocal",
    ast.AsyncFunctionDef: "async def",
    ast.AsyncFor: "async for",
    ast.AsyncWith: "async with",
    ast.Await: "await",
    ast.Set: "set display (hash-order nondeterminism)",
    ast.SetComp: "set comprehension (hash-order nondeterminism)",
    ast.With: "with",
}


def validate(source: str) -> ast.Module:
    """Parse + whitelist-check contract source; returns the AST."""
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        raise SandboxViolation(f"syntax error: {e}") from e
    for node in ast.walk(tree):
        for banned, label in _BANNED_NODES.items():
            if isinstance(node, banned):
                raise SandboxViolation(
                    f"line {getattr(node, 'lineno', '?')}: {label} "
                    f"is not allowed in sandboxed contract code")
        if isinstance(node, ast.Attribute) and node.attr.startswith("_"):
            raise SandboxViolation(
                f"line {node.lineno}: access to underscore attribute "
                f"{node.attr!r} is not allowed")
        if isinstance(node, ast.Name) and node.id.startswith("__"):
            raise SandboxViolation(
                f"line {node.lineno}: dunder name {node.id!r} is not allowed")
    return tree


class _CostTransformer(ast.NodeTransformer):
    """Rewrite so execution charges the budget: a __charge__() call before
    every statement, and every for/comprehension iterable wrapped in the
    charging iterator (per-iteration accounting, the per-instruction
    accounting analog)."""

    CHARGE = "_sandbox_charge"
    ITER = "_sandbox_iter"

    def _charge_stmt(self, at) -> ast.Expr:
        return ast.copy_location(ast.Expr(ast.Call(
            ast.Name(self.CHARGE, ast.Load()), [], [])), at)

    def _rewrite_body(self, body: list) -> list:
        out = []
        for stmt in body:
            stmt = self.visit(stmt)
            out.append(self._charge_stmt(stmt))
            out.append(stmt)
        return out

    def visit_Module(self, node):
        node.body = self._rewrite_body(node.body)
        return node

    def visit_FunctionDef(self, node):
        node.body = self._rewrite_body(node.body)
        return node

    def visit_For(self, node):
        node.iter = ast.copy_location(ast.Call(
            ast.Name(self.ITER, ast.Load()), [self.visit(node.iter)], []),
            node.iter)
        node.body = self._rewrite_body(node.body)
        node.orelse = self._rewrite_body(node.orelse)
        return node

    def visit_While(self, node):
        node.test = self.visit(node.test)
        node.body = self._rewrite_body(node.body)
        node.orelse = self._rewrite_body(node.orelse)
        return node

    def _wrap_comp(self, node):
        node = self.generic_visit(node)
        for gen in node.generators:
            gen.iter = ast.copy_location(ast.Call(
                ast.Name(self.ITER, ast.Load()), [gen.iter], []), gen.iter)
        return node

    visit_ListComp = _wrap_comp
    visit_DictComp = _wrap_comp
    visit_GeneratorExp = _wrap_comp


@dataclass
class DeterministicSandbox:
    """Load + run contract code under the whitelist and an instruction budget
    (RuntimeCostAccounter role; budget = charged statements + iterations)."""

    instruction_budget: int = 1_000_000

    def load(self, source: str, bindings: dict | None = None) -> dict:
        """Validate, instrument, and execute a contract module's top level.
        Returns its namespace; classes/functions defined there keep charging
        against this sandbox's budget when called later. ``bindings`` are
        extra names made visible (the framework types the contract needs —
        the whitelisted-classes analog)."""
        tree = validate(source)
        tree = _CostTransformer().visit(tree)
        ast.fix_missing_locations(tree)
        code = compile(tree, "<sandboxed-contract>", "exec")
        self._spent = 0

        def charge():
            self._spent += 1
            if self._spent > self.instruction_budget:
                raise SandboxCostExceeded(
                    f"instruction budget {self.instruction_budget} exhausted")

        def charged_iter(it):
            for item in iter(it):
                charge()
                yield item

        def _builtin(name):
            return (__builtins__[name] if isinstance(__builtins__, dict)
                    else getattr(__builtins__, name))

        safe_builtins = {name: _builtin(name) for name in _SAFE_BUILTIN_NAMES}
        # class-statement machinery (builds only already-validated code)
        safe_builtins["__build_class__"] = _builtin("__build_class__")
        namespace = {
            "__builtins__": safe_builtins,
            "__name__": "sandboxed_contract",
            _CostTransformer.CHARGE: charge,
            _CostTransformer.ITER: charged_iter,
        }
        namespace.update(bindings or {})
        exec(code, namespace)
        return namespace

    @property
    def spent(self) -> int:
        return getattr(self, "_spent", 0)

    def run(self, fn, *args, **kwargs):
        """Call a function loaded by this sandbox (charging continues)."""
        return fn(*args, **kwargs)
