"""Deterministic sandbox for contract verification code.

Reference parity: experimental/sandbox — the prototype deterministic JVM
sandbox for contract code (WhitelistClassLoader.java:1-356: whitelist class
loading + ASM bytecode rewriting; visitors/CostInstrumentingMethodVisitor +
costing/RuntimeCostAccounter: runtime cost accounting that kills runaway
code). The TPU build's contract bodies are Python, so the same two defenses
become:

- **Whitelist validation** (the WhitelistClassLoader role): contract source
  is parsed to an AST and rejected unless every construct is on the
  whitelist — no imports outside the allowed set, no dunder/underscore
  attribute access, no global/nonlocal, no async, no set displays (string
  hashing is process-seeded, so set iteration order is nondeterministic),
  and execution sees only a curated builtins table (no eval/exec/open/
  getattr/globals/hash/id/print...).
- **Cost accounting** (the CostInstrumentingMethodVisitor role): the AST is
  rewritten before compilation so every statement charges the instruction
  budget and every loop/comprehension iterates through a charging iterator;
  exhausting the budget raises SandboxCostExceeded mid-flight, exactly like
  the reference's TerminateException on runtime-cost thresholds.

Determinism, not security isolation, is the goal (same stance as the
reference prototype): the sandbox guarantees that a contract either
produces the same verdict on every node or dies the same way on every node.
"""
from __future__ import annotations

import ast
import re
from dataclasses import dataclass


class SandboxViolation(Exception):
    """Contract source uses a construct outside the deterministic whitelist."""


class SandboxCostExceeded(BaseException):
    """The in-flight budget kill raised inside sandboxed frames.

    Derives from BaseException (not Exception) so sandboxed ``except
    Exception`` handlers cannot swallow it — the budget kill must always
    propagate out of the contract, mirroring the reference's
    ThreadDeath-style TerminateException which user code cannot catch.
    At the sandbox boundary (``load``/``run``) it is rewrapped into
    :class:`SandboxBudgetError` so HOST code keeps ordinary
    ``except Exception`` semantics (a budget-killed contract becomes a
    normal verification failure, not a worker-killing BaseException)."""


class SandboxBudgetError(Exception):
    """Host-facing form of a budget kill, raised by ``load``/``run``."""


_SAFE_BUILTIN_NAMES = (
    "abs", "all", "any", "bool", "bytes", "chr", "dict", "divmod",
    "enumerate", "filter", "float", "format", "int", "isinstance",
    "issubclass", "iter", "len", "list", "map", "max", "min", "next", "ord",
    "pow", "range", "repr", "reversed", "round", "slice", "sorted", "str",
    "sum", "tuple", "zip",
    # exceptions contract code may raise/catch
    "Exception", "ValueError", "TypeError", "ArithmeticError",
    "AssertionError", "ZeroDivisionError", "StopIteration", "IndexError",
    "KeyError",
)

_BANNED_NODES = {
    ast.Import: "import",
    ast.ImportFrom: "import",
    ast.Global: "global",
    ast.Nonlocal: "nonlocal",
    ast.AsyncFunctionDef: "async def",
    ast.AsyncFor: "async for",
    ast.AsyncWith: "async with",
    ast.Await: "await",
    ast.Set: "set display (hash-order nondeterminism)",
    ast.SetComp: "set comprehension (hash-order nondeterminism)",
    ast.With: "with",
    # match-statement capture patterns (MatchAs.name / MatchStar.name /
    # MatchMapping.rest) carry raw string binding names that the ast.Name
    # underscore ban never sees — `match int:\n case _sandbox_charge: pass`
    # would rebind the injected charge hook and neutralize the budget
    # (ADVICE r2 high). Ban the whole statement, consistent with the
    # minimal deterministic whitelist.
    ast.Match: "match statement",
}

# String methods whose one-call output size is set by an integer width
# argument (ADVICE r2 medium: 'a'.ljust(200_000_000) allocates 200 MB for
# ~2 charged units), plus the .format/.format_map methods whose spec string
# smuggles the same width ('{:>200000000}'.format(1)). Banned outright;
# contract code uses the guarded format() builtin instead.
_WIDTH_METHODS = frozenset({
    "ljust", "rjust", "center", "zfill", "expandtabs",
    "format", "format_map",
})

# Largest width a format spec / %-format may request: big enough for any
# honest tabular output, far below an allocation attack.
_MAX_FORMAT_WIDTH = 10_000
_MAX_WIDTH_DIGITS = len(str(_MAX_FORMAT_WIDTH))

# %-format conversion specs: width/precision groups only — literal digits in
# the template text ("block 20260730: %d") are NOT padding and must not count.
_PERCENT_SPEC = re.compile(
    r"%(?:\([^)]*\))?[-+ #0]*(\*|\d+)?(?:\.(\*|\d+))?[hlL]*"
    r"[diouxXeEfFgGcrsab%]")


def _spec_width(spec: str) -> int:
    """Total of the integer runs in a format()/f-string spec string — an
    upper bound on the padding it can demand. Runs longer than the cap's
    digit count are reported as over-cap WITHOUT calling int() (CPython's
    int-to-str digit limit raises ValueError past 4300 digits, and that
    limit is per-process configurable — a determinism hazard)."""
    total = 0
    for run in re.findall(r"\d+", spec):
        if len(run) > _MAX_WIDTH_DIGITS:
            return _MAX_FORMAT_WIDTH + 1
        total += int(run)
    return total


def _percent_width(template: str) -> int:
    """Upper bound on the padding a %-format template demands, scanning
    only the width/precision of actual conversion specs. A ``*`` width
    (taken from the argument tuple at runtime) cannot be priced statically
    and is refused."""
    total = 0
    for width, precision in _PERCENT_SPEC.findall(template):
        for part in (width, precision):
            if part == "*":
                raise SandboxCostExceeded(
                    "dynamic '*' width in %-formatting is not allowed")
            if part:
                if len(part) > _MAX_WIDTH_DIGITS:
                    return _MAX_FORMAT_WIDTH + 1
                total += int(part)
    return total


def validate(source: str) -> ast.Module:
    """Parse + whitelist-check contract source; returns the AST."""
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        raise SandboxViolation(f"syntax error: {e}") from e
    for node in ast.walk(tree):
        for banned, label in _BANNED_NODES.items():
            if isinstance(node, banned):
                raise SandboxViolation(
                    f"line {getattr(node, 'lineno', '?')}: {label} "
                    f"is not allowed in sandboxed contract code")
        if isinstance(node, ast.Attribute) and node.attr.startswith("_"):
            raise SandboxViolation(
                f"line {node.lineno}: access to underscore attribute "
                f"{node.attr!r} is not allowed")
        if isinstance(node, ast.Attribute) and node.attr in _WIDTH_METHODS:
            raise SandboxViolation(
                f"line {node.lineno}: {node.attr!r} is not allowed in "
                f"sandboxed contract code (unbounded-width formatting; "
                f"use the format() builtin)")
        # f-string format specs are the same width surface as format():
        # reject dynamic specs and oversized constant widths up front.
        if isinstance(node, ast.FormattedValue) and \
                node.format_spec is not None:
            parts = []
            for piece in node.format_spec.values:
                if not isinstance(piece, ast.Constant):
                    raise SandboxViolation(
                        f"line {node.lineno}: dynamic f-string format "
                        f"spec is not allowed")
                parts.append(str(piece.value))
            if _spec_width("".join(parts)) > _MAX_FORMAT_WIDTH:
                raise SandboxViolation(
                    f"line {node.lineno}: f-string format width exceeds "
                    f"{_MAX_FORMAT_WIDTH}")
        # ANY underscore-prefixed name is banned (not just dunders): the
        # cost-accounting hooks are injected under single-underscore names
        # after validation, so user source must never be able to name (and
        # thus rebind or shadow) them.
        if isinstance(node, ast.Name) and node.id.startswith("_"):
            raise SandboxViolation(
                f"line {node.lineno}: underscore name {node.id!r} "
                f"is not allowed")
        if isinstance(node, (ast.FunctionDef, ast.ClassDef)) and \
                node.name.startswith("_"):
            raise SandboxViolation(
                f"line {node.lineno}: underscore name {node.name!r} "
                f"is not allowed")
        if isinstance(node, ast.arg) and node.arg.startswith("_"):
            raise SandboxViolation(
                f"line {node.lineno}: underscore argument {node.arg!r} "
                f"is not allowed")
        if isinstance(node, ast.keyword) and node.arg and \
                node.arg.startswith("_"):
            raise SandboxViolation(
                f"line {node.lineno}: underscore keyword {node.arg!r} "
                f"is not allowed")
        # bare `except:` catches BaseException and could swallow the budget
        # kill; require an explicit (whitelisted, Exception-derived) type.
        if isinstance(node, ast.ExceptHandler):
            if node.type is None:
                raise SandboxViolation(
                    f"line {node.lineno}: bare except is not allowed")
            if node.name and node.name.startswith("_"):
                raise SandboxViolation(
                    f"line {node.lineno}: underscore name {node.name!r} "
                    f"is not allowed")
    return tree


def _as_load(target: ast.expr) -> ast.expr:
    """Deep-copy a Store-context assignment target as a Load expression."""
    copied = ast.parse(ast.unparse(target), mode="eval").body
    return copied


class _CostTransformer(ast.NodeTransformer):
    """Rewrite so execution charges the budget: a __charge__() call before
    every statement, and every for/comprehension iterable wrapped in the
    charging iterator (per-iteration accounting, the per-instruction
    accounting analog)."""

    CHARGE = "_sandbox_charge"
    ITER = "_sandbox_iter"
    BINOP = "_sandbox_binop"

    # operators whose single-statement cost can be unbounded (10**10**8,
    # 'a' * 10**9, 1 << 10**9, repeated s = s + s doubling): routed through
    # a guarded helper that prices the result size against the budget
    # before evaluating.
    _GUARDED_OPS = {ast.Pow: "**", ast.Mult: "*", ast.LShift: "<<",
                    ast.Add: "+", ast.Mod: "%"}

    def visit_BinOp(self, node):
        node = self.generic_visit(node)
        label = self._GUARDED_OPS.get(type(node.op))
        if label is None:
            return node
        return ast.copy_location(ast.Call(
            ast.Name(self.BINOP, ast.Load()),
            [ast.Constant(label), node.left, node.right], []), node)

    def _charge_stmt(self, at) -> ast.Expr:
        return ast.copy_location(ast.Expr(ast.Call(
            ast.Name(self.CHARGE, ast.Load()), [], [])), at)

    def _rewrite_body(self, body: list) -> list:
        out = []
        for stmt in body:
            stmt = self.visit(stmt)
            out.append(self._charge_stmt(stmt))
            out.append(stmt)
        return out

    def visit_Module(self, node):
        node.body = self._rewrite_body(node.body)
        return node

    def visit_FunctionDef(self, node):
        # default-argument and decorator expressions execute at def time —
        # they need the binop guards too, not just the body
        node.args = self.generic_visit(node.args)
        node.decorator_list = [self.visit(d) for d in node.decorator_list]
        node.body = self._rewrite_body(node.body)
        return node

    def visit_AugAssign(self, node):
        # `x **= y` etc. must route through the same guard: desugar to
        # `x = _sandbox_binop("**=", x, y)`. The "=" suffix makes the
        # helper use the IN-PLACE operator (operator.ipow/imul/...), so
        # `b += [2]` still mutates an aliased list exactly as Python does
        # (re-evaluating a subscript/attribute target is acceptable inside
        # the deterministic whitelist).
        if type(node.op) not in self._GUARDED_OPS:
            return self.generic_visit(node)
        label = self._GUARDED_OPS[type(node.op)] + "="
        load_target = ast.copy_location(ast.fix_missing_locations(
            _as_load(node.target)), node.target)
        call = ast.copy_location(ast.Call(
            ast.Name(self.BINOP, ast.Load()),
            [ast.Constant(label), load_target, self.visit(node.value)], []),
            node)
        return ast.copy_location(
            ast.Assign(targets=[node.target], value=call), node)

    def visit_For(self, node):
        node.iter = ast.copy_location(ast.Call(
            ast.Name(self.ITER, ast.Load()), [self.visit(node.iter)], []),
            node.iter)
        node.body = self._rewrite_body(node.body)
        node.orelse = self._rewrite_body(node.orelse)
        return node

    def visit_While(self, node):
        node.test = self.visit(node.test)
        node.body = self._rewrite_body(node.body)
        node.orelse = self._rewrite_body(node.orelse)
        return node

    def _wrap_comp(self, node):
        node = self.generic_visit(node)
        for gen in node.generators:
            gen.iter = ast.copy_location(ast.Call(
                ast.Name(self.ITER, ast.Load()), [gen.iter], []), gen.iter)
        return node

    visit_ListComp = _wrap_comp
    visit_DictComp = _wrap_comp
    visit_GeneratorExp = _wrap_comp


@dataclass
class DeterministicSandbox:
    """Load + run contract code under the whitelist and an instruction budget
    (RuntimeCostAccounter role; budget = charged statements + iterations)."""

    instruction_budget: int = 1_000_000

    def load(self, source: str, bindings: dict | None = None) -> dict:
        """Validate, instrument, and execute a contract module's top level.
        Returns its namespace; classes/functions defined there keep charging
        against this sandbox's budget when called later. ``bindings`` are
        extra names made visible (the framework types the contract needs —
        the whitelisted-classes analog)."""
        tree = validate(source)
        tree = _CostTransformer().visit(tree)
        ast.fix_missing_locations(tree)
        code = compile(tree, "<sandboxed-contract>", "exec")
        self._spent = 0

        def charge(units: int = 1):
            self._spent += units
            if self._spent > self.instruction_budget:
                raise SandboxCostExceeded(
                    f"instruction budget {self.instruction_budget} exhausted")

        def charged_iter(it):
            for item in iter(it):
                charge()
                yield item

        def _size_units(v) -> int:
            """Price an operand: ints by bit length, sized containers by
            length, everything else flat."""
            if isinstance(v, bool):
                return 1
            if isinstance(v, int):
                return max(1, v.bit_length() // 64)
            try:
                return max(1, len(v) // 64)
            except TypeError:
                return 1

        def guarded_binop(op: str, left, right):
            """Evaluate **, *, << or + with the result size pre-charged, so
            a single statement cannot smuggle unbounded work past the
            per-statement accounting (ADVICE r1: `x = 10**10**8`). An "="
            suffix selects the in-place operator, preserving aliased-mutable
            semantics for augmented assignments (`b += [2]`)."""
            import operator as _op
            inplace = op.endswith("=")
            base_op = op[:-1] if inplace else op
            if base_op == "%":
                return guarded_mod(op, left, right)
            if base_op == "**":
                # |base| <= 1 powers are O(1) no matter the exponent
                if isinstance(left, int) and isinstance(right, int) \
                        and not isinstance(left, bool) \
                        and right > 0 and abs(left) > 1:
                    charge(max(1, (abs(left).bit_length() * right) // 64))
                apply = _op.ipow if inplace else _op.pow
            elif base_op == "<<":
                if isinstance(left, int) and isinstance(right, int) \
                        and right > 0 and left != 0:
                    charge(max(1, (abs(left).bit_length() + right) // 64))
                apply = _op.ilshift if inplace else _op.lshift
            elif base_op == "+":
                # sequence concatenation priced by combined length, so
                # `s = s + s` doubling charges exponentially alongside the
                # data and hits the budget long before memory; numeric adds
                # charge their flat statement cost only
                if not isinstance(left, (int, float, complex)):
                    charge(_size_units(left) + _size_units(right))
                apply = _op.iadd if inplace else _op.add
            else:  # '*': sequences replicate, big ints multiply
                if isinstance(right, int) and not isinstance(right, bool):
                    try:
                        n = len(left)
                    except TypeError:
                        n = None
                    if n is not None and right > 0:
                        charge(max(1, (n * right) // 64))
                if isinstance(left, int) and not isinstance(left, bool):
                    try:
                        n = len(right)
                    except TypeError:
                        n = None
                    if n is not None and left > 0:
                        charge(max(1, (n * left) // 64))
                if isinstance(left, int) and isinstance(right, int):
                    charge(max(1,
                               (_size_units(left) + _size_units(right)) // 2))
                apply = _op.imul if inplace else _op.mul
            return apply(left, right)

        def guarded_mod(op: str, left, right):
            """%-formatting prices the widths its spec string demands BEFORE
            evaluating ('%0200000000d' % 1 is a 200 MB allocation for ~2
            charged units otherwise — ADVICE r2). Numeric modulo passes
            through at flat statement cost."""
            import operator as _op
            if isinstance(left, (str, bytes, bytearray)):
                template = (left if isinstance(left, str)
                            else left.decode("latin-1"))
                width = _percent_width(template)
                if width > _MAX_FORMAT_WIDTH:
                    raise SandboxCostExceeded(
                        f"%-format width {width} exceeds "
                        f"{_MAX_FORMAT_WIDTH}")
                charge(max(1, (len(left) + width) // 64))
            return (_op.imod if op.endswith("=") else _op.mod)(left, right)

        def guarded_pow(base, exp, mod=None):
            if mod is not None:
                charge(_size_units(base) + _size_units(exp) +
                       _size_units(mod))
                return pow(base, exp, mod)
            return guarded_binop("**", base, exp)

        def guarded_range(*args):
            r = range(*args)
            # length computed arithmetically: len() overflows past maxsize
            start, stop, step = r.start, r.stop, r.step
            if step > 0:
                n = max(0, (stop - start + step - 1) // step)
            else:
                n = max(0, (start - stop - step - 1) // -step)
            if n > self.instruction_budget:
                raise SandboxCostExceeded(
                    f"range of {n} exceeds instruction budget "
                    f"{self.instruction_budget}")
            # charge proportionally up front: consumers that bypass
            # charged_iter (list(range(n)), sum(range(n))) must not get
            # budget-squared free work out of repeated in-budget ranges
            charge(max(1, n // 64))
            return r

        def guarded_format(value, spec=""):
            """format() with the spec's width priced before evaluation
            (format(1, '>200000000') is a one-call 200 MB allocation
            otherwise — ADVICE r2)."""
            if isinstance(spec, str) and spec:
                width = _spec_width(spec)
                if width > _MAX_FORMAT_WIDTH:
                    raise SandboxCostExceeded(
                        f"format width {width} exceeds {_MAX_FORMAT_WIDTH}")
                charge(max(1, width // 64))
            return format(value, spec)

        def guarded_bytes(*args):
            if args and isinstance(args[0], int) \
                    and not isinstance(args[0], bool):
                charge(max(1, args[0] // 64))
            return bytes(*args)

        def _builtin(name):
            return (__builtins__[name] if isinstance(__builtins__, dict)
                    else getattr(__builtins__, name))

        safe_builtins = {name: _builtin(name) for name in _SAFE_BUILTIN_NAMES}
        # class-statement machinery (builds only already-validated code)
        safe_builtins["__build_class__"] = _builtin("__build_class__")
        # cost-capped replacements for the unbounded-in-one-call builtins
        safe_builtins["pow"] = guarded_pow
        safe_builtins["range"] = guarded_range
        safe_builtins["bytes"] = guarded_bytes
        safe_builtins["format"] = guarded_format
        namespace = {
            "__builtins__": safe_builtins,
            "__name__": "sandboxed_contract",
            _CostTransformer.CHARGE: charge,
            _CostTransformer.ITER: charged_iter,
            _CostTransformer.BINOP: guarded_binop,
        }
        namespace.update(bindings or {})
        try:
            exec(code, namespace)
        except SandboxCostExceeded as e:
            raise SandboxBudgetError(str(e)) from None
        return namespace

    @property
    def spent(self) -> int:
        return getattr(self, "_spent", 0)

    def run(self, fn, *args, **kwargs):
        """Call a function loaded by this sandbox (charging continues).

        This is the HOST boundary: a budget kill (BaseException inside the
        sandbox, uncatchable there) surfaces as :class:`SandboxBudgetError`
        (a plain Exception) so verifier/flow error paths handle it like any
        contract failure. Always call sandboxed functions through here."""
        try:
            return fn(*args, **kwargs)
        except SandboxCostExceeded as e:
            raise SandboxBudgetError(str(e)) from None
