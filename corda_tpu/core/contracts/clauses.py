"""Clause framework: composable contract-verification combinators.

Reference parity: core/contracts/clauses/ (11 files) — `Clause` with
required-command matching, `AllOf`/`AnyOf`/`FirstOf` composition, and
`GroupClauseVerifier` applying clauses per in/out state group (the structure
the asset contracts — Cash, CommercialPaper, Obligation — are written in).
"""
from __future__ import annotations

from typing import Any

from .exceptions import TransactionVerificationException


class Clause:
    """One verification rule. Subclasses set `required_commands` (types) and
    implement `verify`, returning the set of command data they consumed."""

    required_commands: tuple[type, ...] = ()

    def matches(self, commands) -> bool:
        if not self.required_commands:
            return True
        present = {type(c.value) for c in commands}
        return all(any(issubclass(p, rc) for p in present)
                   for rc in self.required_commands)

    def get_execution_path(self, commands) -> list["Clause"]:
        return [self]

    def verify(self, tx, inputs, outputs, commands, grouping_key) -> set:
        raise NotImplementedError

    def __repr__(self):
        return type(self).__name__


class CompositeClause(Clause):
    def __init__(self, *clauses: Clause):
        self.clauses = clauses

    def get_execution_path(self, commands) -> list[Clause]:
        out = []
        for c in self.clauses:
            out.extend(c.get_execution_path(commands))
        return out

    def __repr__(self):
        inner = ", ".join(repr(c) for c in self.clauses)
        return f"{type(self).__name__}({inner})"


class AllOf(CompositeClause):
    """Every member clause must match and verify (AllOf.kt)."""

    def matches(self, commands) -> bool:
        return all(c.matches(commands) for c in self.clauses)

    def verify(self, tx, inputs, outputs, commands, grouping_key) -> set:
        if not self.matches(commands):
            raise TransactionVerificationException(
                getattr(tx, "id", None), f"Required commands not present for {self}")
        matched = set()
        for clause in self.clauses:
            matched |= clause.verify(tx, inputs, outputs, commands, grouping_key)
        return matched


class AnyOf(CompositeClause):
    """One or more matching members run (AnyOf.kt)."""

    def matches(self, commands) -> bool:
        return any(c.matches(commands) for c in self.clauses)

    def verify(self, tx, inputs, outputs, commands, grouping_key) -> set:
        matched = set()
        ran = 0
        for clause in self.clauses:
            if clause.matches(commands):
                matched |= clause.verify(tx, inputs, outputs, commands, grouping_key)
                ran += 1
        if ran == 0:
            raise TransactionVerificationException(
                getattr(tx, "id", None), f"No matching clause in {self}")
        return matched


class FirstOf(CompositeClause):
    """The first matching member runs (FirstOf.kt / FirstComposition)."""

    def verify(self, tx, inputs, outputs, commands, grouping_key) -> set:
        for clause in self.clauses:
            if clause.matches(commands):
                return clause.verify(tx, inputs, outputs, commands, grouping_key)
        raise TransactionVerificationException(
            getattr(tx, "id", None), f"No matching clause in {self}")


class GroupClauseVerifier(Clause):
    """Applies an inner clause to each state group (GroupClauseVerifier.kt).
    Subclasses implement `group_states(tx)` returning InOutGroups."""

    def __init__(self, clause: Clause):
        self.clause = clause

    def group_states(self, tx):
        raise NotImplementedError

    def verify(self, tx, inputs, outputs, commands, grouping_key) -> set:
        matched = set()
        for group in self.group_states(tx):
            matched |= self.clause.verify(tx, group.inputs, group.outputs,
                                          commands, group.grouping_key)
        return matched


def verify_clause(tx, main_clause: Clause, commands) -> None:
    """Top-level driver (ClauseVerifier.kt verifyClause): run the clause tree
    over this contract's commands (the caller pre-filters to its own command
    types, as the reference's extractCommands does), then require every one of
    them to have been matched by some clause."""
    matched = main_clause.verify(tx, getattr(tx, "inputs", ()),
                                 getattr(tx, "outputs", ()), commands, None)
    unmatched = [c for c in commands if c.value not in matched]
    if unmatched:
        raise TransactionVerificationException(
            getattr(tx, "id", None),
            f"Commands not matched by any clause: {unmatched}")
