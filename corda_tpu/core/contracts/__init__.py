"""The ledger algebra: states, commands, amounts, transaction-verification rules.

Reference parity: core/.../contracts/ (Structures.kt, Amount.kt,
TransactionVerification.kt, TransactionTypes.kt, clauses/).
"""
from .structures import (
    Contract, ContractState, OwnableState, FungibleAsset, LinearState, SchedulableState,
    ScheduledActivity, TransactionState, StateRef, StateAndRef, Command,
    AuthenticatedObject, CommandData, TypeOnlyCommandData, MoveCommand, IssueCommand,
    ExitCommand, TimeWindow, PartyAndReference, Issued, UniqueIdentifier, Attachment,
    requireThat,
)
from .amount import Amount, Currency, USD, GBP, EUR, CHF
from .exceptions import (
    TransactionVerificationException, TransactionResolutionException,
    AttachmentResolutionException, ContractRejection, MoreThanOneNotary,
    SignersMissing, DuplicateInputStates, InvalidNotaryChange,
    NotaryChangeInWrongTransactionType, TransactionMissingEncumbranceException,
)
from .transaction_types import TransactionType

__all__ = [
    "Contract", "ContractState", "OwnableState", "FungibleAsset", "LinearState",
    "SchedulableState", "ScheduledActivity", "TransactionState", "StateRef",
    "StateAndRef", "Command", "AuthenticatedObject", "CommandData",
    "TypeOnlyCommandData", "MoveCommand", "IssueCommand", "ExitCommand", "TimeWindow",
    "PartyAndReference", "Issued", "UniqueIdentifier", "Attachment", "requireThat",
    "Amount", "Currency", "USD", "GBP", "EUR", "CHF",
    "TransactionVerificationException", "TransactionResolutionException",
    "AttachmentResolutionException", "ContractRejection", "MoreThanOneNotary",
    "SignersMissing", "DuplicateInputStates", "InvalidNotaryChange",
    "NotaryChangeInWrongTransactionType", "TransactionMissingEncumbranceException",
    "TransactionType",
]
