"""Per-transaction-type platform validation rules.

Reference parity: core/.../contracts/TransactionTypes.kt:1-177 — rule-for-rule:
signers present, single notary, no duplicate inputs, encumbrance integrity, contract
verify dispatch (General) / unmodified-but-notary check (NotaryChange).
"""
from __future__ import annotations

from ..serialization import serializable
from .exceptions import (
    ContractRejection, DuplicateInputStates, InvalidNotaryChange,
    MoreThanOneNotary, NotaryChangeInWrongTransactionType, SignersMissing,
    TransactionMissingEncumbranceException, TransactionVerificationException,
)


class TransactionType:
    """Singleton strategy objects: ``TransactionType.General`` and
    ``TransactionType.NotaryChange``."""

    General: "TransactionType"
    NotaryChange: "TransactionType"

    def verify(self, tx) -> None:
        """Platform rules common to all types, then type-specific rules.
        Presence of *signatures* is NOT checked here — only required keys
        (TransactionTypes.kt:21-28)."""
        if tx.notary is None and tx.time_window is not None:
            raise TransactionVerificationException(
                tx.id, "Transactions with time-windows must be notarised")
        duplicates = self._detect_duplicate_inputs(tx)
        if duplicates:
            raise DuplicateInputStates(tx.id, duplicates)
        missing = self.verify_signers(tx)
        if missing:
            raise SignersMissing(tx.id, sorted(missing))
        self.verify_transaction(tx)

    def verify_signers(self, tx) -> set:
        notary_keys = {inp.state.notary.owning_key for inp in tx.inputs}
        if len(notary_keys) > 1:
            raise MoreThanOneNotary(tx.id)
        required = self.get_required_signers(tx) | notary_keys
        return required - set(tx.must_sign)

    @staticmethod
    def _detect_duplicate_inputs(tx) -> set:
        seen, dups = set(), set()
        for inp in tx.inputs:
            if inp.ref in seen:
                dups.add(inp.ref)
            seen.add(inp.ref)
        return dups

    def get_required_signers(self, tx) -> set:
        raise NotImplementedError

    def verify_transaction(self, tx) -> None:
        raise NotImplementedError

    def __eq__(self, other):
        return type(self) is type(other)

    def __hash__(self):
        return hash(type(self))

    def __repr__(self):
        return f"TransactionType.{type(self).__name__.lstrip('_')}"


@serializable("TransactionType.General", to_fields=lambda t: [],
              from_fields=lambda f: TransactionType.General)
class _General(TransactionType):
    def get_required_signers(self, tx) -> set:
        return {k for cmd in tx.commands for k in cmd.signers}

    def verify_transaction(self, tx) -> None:
        self._verify_no_notary_change(tx)
        self._verify_encumbrances(tx)
        self._verify_contracts(tx)

    @staticmethod
    def _verify_no_notary_change(tx):
        if tx.notary is not None and tx.inputs:
            for out in tx.outputs:
                if out.notary != tx.notary:
                    raise NotaryChangeInWrongTransactionType(tx.id, tx.notary, out.notary)

    @staticmethod
    def _verify_encumbrances(tx):
        for inp in tx.inputs:
            enc = inp.state.encumbrance
            if enc is None:
                continue
            if not any(o.ref.txhash == inp.ref.txhash and o.ref.index == enc
                       for o in tx.inputs):
                raise TransactionMissingEncumbranceException(
                    tx.id, enc, TransactionMissingEncumbranceException.INPUT)
        for i, out in enumerate(tx.outputs):
            enc = out.encumbrance
            if enc is None:
                continue
            if enc < 0 or enc == i or enc >= len(tx.outputs):
                raise TransactionMissingEncumbranceException(
                    tx.id, enc, TransactionMissingEncumbranceException.OUTPUT)

    @staticmethod
    def _verify_contracts(tx):
        ctx = tx.to_transaction_for_contract()
        contracts = []
        for st in list(ctx.inputs) + list(ctx.outputs):
            if st.contract not in contracts:
                contracts.append(st.contract)
        for contract in contracts:
            try:
                contract.verify(ctx)
            except Exception as e:
                raise ContractRejection(tx.id, contract, e) from e


@serializable("TransactionType.NotaryChange", to_fields=lambda t: [],
              from_fields=lambda f: TransactionType.NotaryChange)
class _NotaryChange(TransactionType):
    def get_required_signers(self, tx) -> set:
        return {k.owning_key if hasattr(k, "owning_key") else k
                for inp in tx.inputs for k in inp.state.data.participants}

    def verify_transaction(self, tx) -> None:
        ok = (len(tx.inputs) == len(tx.outputs) and not tx.commands and all(
            inp.state.data == out.data and inp.state.notary != out.notary
            for inp, out in zip(tx.inputs, tx.outputs)))
        if not ok:
            raise InvalidNotaryChange(tx.id)


TransactionType.General = _General()
TransactionType.NotaryChange = _NotaryChange()
