"""Core ledger structures: states, commands, time-windows, attachments.

Reference parity: core/.../contracts/Structures.kt:1-491.
"""
from __future__ import annotations

import uuid
from dataclasses import dataclass, field
from datetime import datetime, timedelta, timezone
from typing import Any, Protocol, runtime_checkable

from ..crypto.keys import PublicKey
from ..crypto.secure_hash import SecureHash
from ..identity import AbstractParty, Party
from ..serialization import serializable, serialize


# ---------------------------------------------------------------------------
# Contracts and states
# ---------------------------------------------------------------------------

class Contract:
    """Code that verifies state transitions. Subclass and override ``verify``.

    Contract singletons are serialized by registered type name; ``verify`` bodies
    always run on the HOST (the TPU handles signatures + hashing — SURVEY.md §3.3).
    """

    #: Hash of the legal prose this code implements (Structures.kt legalContractReference).
    legal_contract_reference: SecureHash = SecureHash.sha256(b"corda_tpu.contract")

    def verify(self, tx: "TransactionForContract") -> None:
        raise NotImplementedError

    def __eq__(self, other):
        return type(self) is type(other)

    def __hash__(self):
        return hash(type(self))

    def __repr__(self):
        return type(self).__name__


class ContractState:
    """A fact on the ledger. Subclasses must expose ``contract`` and ``participants``."""

    @property
    def contract(self) -> Contract:
        raise NotImplementedError

    @property
    def participants(self) -> list[PublicKey]:
        raise NotImplementedError


class OwnableState(ContractState):
    """A state with a single owner key, supporting ownership transfer.

    Interface contract (duck-typed so dataclass subclasses can declare the
    attributes as fields): `owner: PublicKey`, and
    `with_new_owner(new_owner) -> (CommandData, OwnableState)`.
    """

    def with_new_owner(self, new_owner: PublicKey) -> tuple["CommandData", "OwnableState"]:
        raise NotImplementedError


class LinearState(ContractState):
    """A state evolving through a chain of transactions, tracked by a
    `linear_id: UniqueIdentifier` attribute (duck-typed, see OwnableState)."""

    def is_relevant(self, our_keys: set[PublicKey]) -> bool:
        return any(k in our_keys for p in self.participants for k in p.keys)


class FungibleAsset(OwnableState):
    """An ownable, splittable/mergeable amount of an issued product (Cash etc.).

    Interface contract: `amount: Amount[Issued[T]]`, `exit_keys: set[PublicKey]`
    (duck-typed, see OwnableState).
    """


@serializable("ScheduledActivity")
@dataclass(frozen=True)
class ScheduledActivity:
    """What to do when a scheduled state fires: start this flow at this time."""

    flow_ref: Any  # FlowLogicRef wire form
    scheduled_at: datetime


class SchedulableState(ContractState):
    def next_scheduled_activity(self, this_state_ref: "StateRef",
                                flow_logic_ref_factory) -> ScheduledActivity | None:
        raise NotImplementedError


@serializable("UniqueIdentifier")
@dataclass(frozen=True, order=True)
class UniqueIdentifier:
    external_id: str | None = None
    id: str = field(default_factory=lambda: str(uuid.uuid4()))

    def __str__(self):
        return f"{self.external_id}_{self.id}" if self.external_id else self.id


@serializable("TransactionState")
@dataclass(frozen=True)
class TransactionState:
    """A ContractState plus ledger-level metadata: the notary in charge of it and an
    optional encumbrance link to another output of the same transaction."""

    data: ContractState
    notary: Party
    encumbrance: int | None = None

    def __post_init__(self):
        if self.encumbrance is not None and self.encumbrance < 0:
            raise ValueError("Encumbrance index must be non-negative")


@serializable("StateRef")
@dataclass(frozen=True, order=True)
class StateRef:
    """Pointer to an output state: (transaction id, output index)."""

    txhash: SecureHash
    index: int

    def __str__(self):
        return f"{self.txhash}({self.index})"


@serializable("StateAndRef")
@dataclass(frozen=True)
class StateAndRef:
    state: TransactionState
    ref: StateRef


# ---------------------------------------------------------------------------
# Commands
# ---------------------------------------------------------------------------

class CommandData:
    """Marker base for command payloads."""


class TypeOnlyCommandData(CommandData):
    """A command whose meaning is entirely its type (Move, Issue, …)."""

    def __eq__(self, other):
        return type(self) is type(other)

    def __hash__(self):
        return hash(type(self))

    def __repr__(self):
        return type(self).__name__


class MoveCommand(CommandData):
    """Marker: commands that move ownership (contract upgrades inspect these)."""


class IssueCommand(CommandData):
    """Marker: commands that issue new value; carries an anti-replay nonce."""

    nonce: int


class ExitCommand(CommandData):
    """Marker: commands that remove value from the ledger."""


@serializable("Command")
@dataclass(frozen=True)
class Command:
    """A command payload plus the keys required to sign for it."""

    value: CommandData
    signers: tuple[PublicKey, ...]

    def __post_init__(self):
        signers = self.signers
        if isinstance(signers, PublicKey):
            signers = (signers,)
        object.__setattr__(self, "signers", tuple(signers))
        if not self.signers:
            raise ValueError("Command must have at least one signer")


@dataclass(frozen=True)
class AuthenticatedObject:
    """A command as seen during verification: payload + signer keys + resolved
    well-known signer identities."""

    signers: tuple[PublicKey, ...]
    signing_parties: tuple[Party, ...]
    value: CommandData


# ---------------------------------------------------------------------------
# Time windows
# ---------------------------------------------------------------------------

@serializable("TimeWindow", to_fields=lambda tw: [tw.from_time, tw.until_time],
              from_fields=lambda f: TimeWindow(f[0], f[1]))
class TimeWindow:
    """An interval the notary attests the transaction fell within.

    Instants serialize as epoch-microsecond ints (determinism: no float seconds).
    """

    __slots__ = ("from_time", "until_time")

    def __init__(self, from_time: datetime | int | None,
                 until_time: datetime | int | None):
        if from_time is None and until_time is None:
            raise ValueError("TimeWindow must have at least one bound")
        self.from_time = _to_micros(from_time)
        self.until_time = _to_micros(until_time)

    @staticmethod
    def between(from_time: datetime, until_time: datetime) -> "TimeWindow":
        return TimeWindow(from_time, until_time)

    @staticmethod
    def from_only(from_time: datetime) -> "TimeWindow":
        return TimeWindow(from_time, None)

    @staticmethod
    def until_only(until_time: datetime) -> "TimeWindow":
        return TimeWindow(None, until_time)

    @staticmethod
    def with_tolerance(instant: datetime, tolerance: timedelta) -> "TimeWindow":
        return TimeWindow(instant - tolerance, instant + tolerance)

    @property
    def midpoint(self) -> datetime | None:
        if self.from_time is None or self.until_time is None:
            return None
        return _from_micros((self.from_time + self.until_time) // 2)

    def contains(self, instant: datetime) -> bool:
        micros = _to_micros(instant)
        if self.from_time is not None and micros < self.from_time:
            return False
        if self.until_time is not None and micros >= self.until_time:
            return False
        return True

    def __eq__(self, other):
        return (isinstance(other, TimeWindow) and self.from_time == other.from_time
                and self.until_time == other.until_time)

    def __hash__(self):
        return hash((self.from_time, self.until_time))

    def __repr__(self):
        return f"TimeWindow({_from_micros(self.from_time)} .. {_from_micros(self.until_time)})"


def _to_micros(t) -> int | None:
    if t is None or isinstance(t, int):
        return t
    from ..serialization.codec import exact_epoch_micros
    return exact_epoch_micros(t)


def _from_micros(m: int | None) -> datetime | None:
    return None if m is None else datetime.fromtimestamp(m / 1_000_000, tz=timezone.utc)


# ---------------------------------------------------------------------------
# Issuance
# ---------------------------------------------------------------------------

@serializable("PartyAndReference")
@dataclass(frozen=True)
class PartyAndReference:
    """An issuer party plus an opaque reference (e.g. an internal account id)."""

    party: AbstractParty
    reference: bytes

    def __str__(self):
        return f"{self.party}{self.reference.hex()}"


@serializable("Issued")
@dataclass(frozen=True)
class Issued:
    """A product (currency, commodity, …) tagged with who issued it."""

    issuer: PartyAndReference
    product: Any

    def __str__(self):
        return f"{self.product} issued by {self.issuer}"


# ---------------------------------------------------------------------------
# Attachments
# ---------------------------------------------------------------------------

@serializable("Attachment", to_fields=lambda a: [a.id, a.data],
              from_fields=lambda f: Attachment(f[0], f[1]))
class Attachment:
    """An immutable blob identified by its hash (reference: jar files; here any
    content-addressed bytes)."""

    __slots__ = ("id", "data")

    def __init__(self, id: SecureHash, data: bytes):
        self.id = id
        self.data = data

    @staticmethod
    def of(data: bytes) -> "Attachment":
        return Attachment(SecureHash.sha256(data), data)

    def verify(self) -> bool:
        return SecureHash.sha256(self.data) == self.id

    def __eq__(self, other):
        return isinstance(other, Attachment) and self.id == other.id

    def __hash__(self):
        return hash(self.id)


# ---------------------------------------------------------------------------
# The `requireThat` contract-DSL helper
# ---------------------------------------------------------------------------

class _Requirements:
    def using(self, message: str, expr: bool):
        if not expr:
            raise ValueError(f"Failed requirement: {message}")

    # pythonic alias
    def that(self, message: str, expr: bool):
        self.using(message, expr)


def requireThat(fn=None):
    """``requireThat(lambda r: r.using("msg", cond))`` or used as a context manager:

    >>> with requireThat() as r:
    ...     r.using("must be positive", x > 0)
    """
    if fn is not None:
        fn(_Requirements())
        return None
    import contextlib

    @contextlib.contextmanager
    def ctx():
        yield _Requirements()

    return ctx()


def tx_time_micros(tx) -> int | None:
    """A transaction's attested instant: the time-window midpoint (or single
    bound) in epoch micros — what time-sensitive contract rules (maturity,
    default) check against. TimeWindow bounds are integer micros."""
    tw = getattr(tx, "time_window", None)
    if tw is None:
        return None
    if tw.from_time is not None and tw.until_time is not None:
        return (tw.from_time + tw.until_time) // 2
    return tw.from_time if tw.from_time is not None else tw.until_time
