"""SPHINCS-256: stateless hash-based (post-quantum) signatures.

Reference parity: the SPHINCS256_SHA512_256 scheme (reference Crypto.kt:139-156,
registered via BouncyCastle's SPHINCS-256 signer with a SHA-512/256 tree
digest). Same construction and parameters as the SPHINCS-256 paper (Bernstein
et al., EUROCRYPT 2015): WOTS+ (w = 16) one-time signatures, HORST (t = 2^16,
k = 32) few-time signatures at the bottom, and a 60-level hypertree split into
d = 12 layers of height 5. Two deliberate deviations, documented because they
change the byte format (not the construction):

- Tweakable hashing a la SPHINCS+: F/H/PRF are SHA-512/256 over an explicit
  (tag, address) prefix instead of the paper's ChaCha12 permutation with XOR
  bitmasks. Same 256-bit interfaces; the digest is the one the scheme name
  commits to; domain separation comes from the address, which every hash call
  binds to its position in the hypertree.
- WOTS+ public keys compress with one wide hash instead of an L-tree, and
  HORST reveals full-height auth paths (no level-6 truncation): simpler
  verification, slightly larger signatures (~45 KB vs 41 KB).

Signatures therefore verify only within this framework — consistent with the
canonical codec replacing Kryo everywhere else (SURVEY.md §7 phase 0).

Layout
------
private key: sk_seed(32) ‖ sk_prf(32) ‖ pub_seed(32)
public key:  pub_seed(32) ‖ root(32)
signature:   R(32) ‖ HORST[k × (sk(32) ‖ auth(16×32))] ‖
             d × (WOTS[67×32] ‖ auth(5×32))
"""
from __future__ import annotations

import hashlib

N = 32                  # hash output bytes
W_LOG = 4               # WOTS+ Winternitz log2(w)
W = 1 << W_LOG
WOTS_L1 = 64            # 256 / W_LOG message digits
WOTS_L2 = 3             # checksum digits: max 64*15 = 960 < 16^3
WOTS_LEN = WOTS_L1 + WOTS_L2
HORST_LOGT = 16         # t = 2^16 leaves
HORST_K = 32            # revealed leaves per signature
LAYERS = 12             # hypertree layers
SUB_H = 5               # per-layer subtree height
TREE_H = LAYERS * SUB_H  # 60
HORST_LAYER = LAYERS     # address byte for the HORST instances

SIG_LEN = (N + HORST_K * (N + HORST_LOGT * N)
           + LAYERS * (WOTS_LEN * N + SUB_H * N))


def _addr(layer: int, tree: int, leaf: int = 0, chain: int = 0,
          pos: int = 0) -> bytes:
    return (bytes([layer]) + tree.to_bytes(8, "big") + leaf.to_bytes(4, "big")
            + chain.to_bytes(2, "big") + pos.to_bytes(2, "big"))


def _hash(tag: bytes, addr: bytes, data: bytes) -> bytes:
    return hashlib.new("sha512_256", tag + addr + data).digest()


def _prf(seed: bytes, addr: bytes) -> bytes:
    return _hash(b"\x00" + seed, addr, b"")


def _f(pub_seed: bytes, addr: bytes, x: bytes) -> bytes:
    return _hash(b"\x01" + pub_seed, addr, x)


def _h2(pub_seed: bytes, addr: bytes, left: bytes, right: bytes) -> bytes:
    return _hash(b"\x02" + pub_seed, addr, left + right)


def _thash(pub_seed: bytes, addr: bytes, data: bytes) -> bytes:
    """Wide compression (WOTS+ pk, message digests)."""
    return _hash(b"\x03" + pub_seed, addr, data)


# ---------------------------------------------------------------------------
# WOTS+
# ---------------------------------------------------------------------------

def _wots_digits(msg32: bytes) -> list[int]:
    digits = []
    for byte in msg32:
        digits.append(byte >> 4)
        digits.append(byte & 15)
    checksum = sum(W - 1 - d for d in digits)
    for shift in (8, 4, 0):
        digits.append((checksum >> shift) & 15)
    return digits


def _chain(pub_seed: bytes, addr_lcl: tuple, x: bytes, start: int,
           steps: int) -> bytes:
    layer, tree, leaf, chain = addr_lcl
    for pos in range(start, start + steps):
        x = _f(pub_seed, _addr(layer, tree, leaf, chain, pos), x)
    return x


def _wots_leaf_from_chains(pub_seed, layer, tree, leaf, ends) -> bytes:
    return _thash(pub_seed, _addr(layer, tree, leaf, 0xFFFF), b"".join(ends))


def _wots_sign(sk_seed, pub_seed, layer, tree, leaf, msg32):
    digits = _wots_digits(msg32)
    sig = []
    for i, d in enumerate(digits):
        sk = _prf(sk_seed, _addr(layer, tree, leaf, i))
        sig.append(_chain(pub_seed, (layer, tree, leaf, i), sk, 0, d))
    return b"".join(sig)


def _wots_leaf_from_sig(pub_seed, layer, tree, leaf, sig: bytes,
                        msg32: bytes) -> bytes:
    digits = _wots_digits(msg32)
    ends = [
        _chain(pub_seed, (layer, tree, leaf, i), sig[i * N:(i + 1) * N],
               d, W - 1 - d)
        for i, d in enumerate(digits)
    ]
    return _wots_leaf_from_chains(pub_seed, layer, tree, leaf, ends)


def _wots_keygen_leaf(sk_seed, pub_seed, layer, tree, leaf) -> bytes:
    ends = []
    for i in range(WOTS_LEN):
        sk = _prf(sk_seed, _addr(layer, tree, leaf, i))
        ends.append(_chain(pub_seed, (layer, tree, leaf, i), sk, 0, W - 1))
    return _wots_leaf_from_chains(pub_seed, layer, tree, leaf, ends)


# ---------------------------------------------------------------------------
# Merkle helpers (shared by HORST and the hypertree subtrees)
# ---------------------------------------------------------------------------

def _build_tree(pub_seed, layer, tree, leaves: list[bytes]):
    """Bottom-up levels; returns (levels, root). levels[0] = leaves."""
    levels = [leaves]
    lvl = 0
    while len(levels[-1]) > 1:
        cur = levels[-1]
        lvl += 1
        nxt = [
            _h2(pub_seed, _addr(layer, tree, i, 0x8000 + lvl), cur[2 * i],
                cur[2 * i + 1])
            for i in range(len(cur) // 2)
        ]
        levels.append(nxt)
    return levels, levels[-1][0]


def _auth_path(levels, leaf_idx: int) -> list[bytes]:
    path = []
    idx = leaf_idx
    for lvl in levels[:-1]:
        path.append(lvl[idx ^ 1])
        idx >>= 1
    return path


def _root_from_auth(pub_seed, layer, tree, leaf_idx: int, node: bytes,
                    path: list[bytes]) -> bytes:
    idx = leaf_idx
    for lvl, sib in enumerate(path, start=1):
        pair = (sib, node) if idx & 1 else (node, sib)
        node = _h2(pub_seed, _addr(layer, tree, idx >> 1, 0x8000 + lvl), *pair)
        idx >>= 1
    return node


# ---------------------------------------------------------------------------
# HORST
# ---------------------------------------------------------------------------

def _horst_sign(horst_seed, pub_seed, tree, selection: list[int]):
    sks = [_prf(horst_seed, _addr(HORST_LAYER, tree, j))
           for j in range(1 << HORST_LOGT)]
    leaves = [_f(pub_seed, _addr(HORST_LAYER, tree, j), sk)
              for j, sk in enumerate(sks)]
    levels, root = _build_tree(pub_seed, HORST_LAYER, tree, leaves)
    sig = b"".join(
        sks[j] + b"".join(_auth_path(levels, j)) for j in selection)
    return sig, root


def _horst_root_from_sig(pub_seed, tree, selection, sig: bytes):
    """Recompute the HORST root from the k revealed (sk, auth) pairs; returns
    None when the revealed paths disagree (forged/corrupt signature)."""
    per = N + HORST_LOGT * N
    root = None
    for i, j in enumerate(selection):
        blob = sig[i * per:(i + 1) * per]
        sk, path_b = blob[:N], blob[N:]
        leaf = _f(pub_seed, _addr(HORST_LAYER, tree, j), sk)
        path = [path_b[l * N:(l + 1) * N] for l in range(HORST_LOGT)]
        r = _root_from_auth(pub_seed, HORST_LAYER, tree, j, leaf, path)
        if root is None:
            root = r
        elif r != root:
            return None
    return root


# ---------------------------------------------------------------------------
# Hypertree + public API
# ---------------------------------------------------------------------------

def _subtree(sk_seed, pub_seed, layer, tree):
    leaves = [_wots_keygen_leaf(sk_seed, pub_seed, layer, tree, leaf)
              for leaf in range(1 << SUB_H)]
    return _build_tree(pub_seed, layer, tree, leaves)


def _message_indices(r: bytes, pub_root: bytes, message: bytes):
    """(R, root, M) → (60-bit hypertree leaf index, k HORST selections)."""
    digest = _hash(b"\x04" + r, b"", pub_root + message)
    stream = b"".join(
        _hash(b"\x05", ctr.to_bytes(4, "big"), digest) for ctr in range(3))
    idx = int.from_bytes(stream[:8], "big") >> (64 - TREE_H)
    selection = [
        int.from_bytes(stream[8 + 2 * i:10 + 2 * i], "big")
        for i in range(HORST_K)
    ]
    return idx, selection


def keygen(entropy: bytes):
    """entropy(32) → (public(64), private(96)). Deterministic."""
    if len(entropy) != 32:
        raise ValueError("SPHINCS-256 keygen needs 32 bytes of entropy")
    sk_seed = _hash(b"\x06", b"sk", entropy)
    sk_prf = _hash(b"\x06", b"pr", entropy)
    pub_seed = _hash(b"\x06", b"pu", entropy)
    _, root = _subtree(sk_seed, pub_seed, LAYERS - 1, 0)
    return pub_seed + root, sk_seed + sk_prf + pub_seed


def sign(private: bytes, message: bytes) -> bytes:
    sk_seed, sk_prf, pub_seed = private[:32], private[32:64], private[64:96]
    _, pub_root = _subtree(sk_seed, pub_seed, LAYERS - 1, 0)
    r = _hash(b"\x07" + sk_prf, b"", message)
    idx, selection = _message_indices(r, pub_root, message)

    horst_seed = _prf(sk_seed, _addr(HORST_LAYER, idx, 0xFFFFFFFF))
    horst_sig, root = _horst_sign(horst_seed, pub_seed, idx, selection)

    parts = [r, horst_sig]
    node_idx = idx
    for layer in range(LAYERS):
        leaf = node_idx & ((1 << SUB_H) - 1)
        tree = node_idx >> SUB_H
        parts.append(_wots_sign(sk_seed, pub_seed, layer, tree, leaf, root))
        levels, root = _subtree(sk_seed, pub_seed, layer, tree)
        parts.append(b"".join(_auth_path(levels, leaf)))
        node_idx = tree
    return b"".join(parts)


def verify(public: bytes, message: bytes, signature: bytes) -> bool:
    if len(public) != 2 * N or len(signature) != SIG_LEN:
        return False
    pub_seed, pub_root = public[:N], public[N:]
    r = signature[:N]
    idx, selection = _message_indices(r, pub_root, message)

    off = N
    horst_len = HORST_K * (N + HORST_LOGT * N)
    root = _horst_root_from_sig(pub_seed, idx,
                                selection, signature[off:off + horst_len])
    if root is None:
        return False
    off += horst_len

    node_idx = idx
    for layer in range(LAYERS):
        leaf = node_idx & ((1 << SUB_H) - 1)
        tree = node_idx >> SUB_H
        wots_sig = signature[off:off + WOTS_LEN * N]
        off += WOTS_LEN * N
        node = _wots_leaf_from_sig(pub_seed, layer, tree, leaf, wots_sig, root)
        path = [signature[off + l * N:off + (l + 1) * N] for l in range(SUB_H)]
        off += SUB_H * N
        root = _root_from_auth(pub_seed, layer, tree, leaf, node, path)
        node_idx = tree
    return root == pub_root
