"""Pure-Python elliptic-curve arithmetic: the host reference implementation.

This module is the *authoritative host semantics* that the batched TPU kernels in
``corda_tpu.ops`` are differentially tested against, and the signing path (signing is
host-side and low-volume; verification is the TPU-batched hot path — reference call
stack SURVEY.md §3.3, Crypto.kt:368-511).

Implemented from the public standards:
- Ed25519: RFC 8032 (EdDSA), curve edwards25519, SHA-512.
- ECDSA over secp256k1 / secp256r1: SEC 1 v2, deterministic nonces per RFC 6979.

No code is taken from the reference repo (which delegates to BouncyCastle/i2p-EdDSA).
"""
from __future__ import annotations

import hashlib
import hmac
from dataclasses import dataclass

# ---------------------------------------------------------------------------
# Ed25519 (RFC 8032)
# ---------------------------------------------------------------------------

ED_P = 2**255 - 19
ED_L = 2**252 + 27742317777372353535851937790883648493
ED_D = (-121665 * pow(121666, ED_P - 2, ED_P)) % ED_P
ED_D2 = (2 * ED_D) % ED_P
# Base point B: y = 4/5, x recovered with sign bit 0.
_ED_BY = (4 * pow(5, ED_P - 2, ED_P)) % ED_P


def _ed_recover_x(y: int, sign: int) -> int | None:
    if y >= ED_P:
        return None
    x2 = (y * y - 1) * pow(ED_D * y * y + 1, ED_P - 2, ED_P) % ED_P
    if x2 == 0:
        return None if sign else 0
    # p % 8 == 5: candidate root x = x2^((p+3)/8)
    x = pow(x2, (ED_P + 3) // 8, ED_P)
    if (x * x - x2) % ED_P != 0:
        x = x * pow(2, (ED_P - 1) // 4, ED_P) % ED_P
    if (x * x - x2) % ED_P != 0:
        return None
    if (x & 1) != sign:
        x = ED_P - x
    return x


_ED_BX = _ed_recover_x(_ED_BY, 0)
ED_B = (_ED_BX, _ED_BY)  # affine base point


def ed_point_add(P, Q):
    """Extended-coordinate unified addition (add-2008-hwcd-3, a=-1 curve)."""
    x1, y1, z1, t1 = P
    x2, y2, z2, t2 = Q
    a = (y1 - x1) * (y2 - x2) % ED_P
    b = (y1 + x1) * (y2 + x2) % ED_P
    c = t1 * ED_D2 * t2 % ED_P
    d = 2 * z1 * z2 % ED_P
    e, f, g, h = b - a, d - c, d + c, b + a
    return (e * f % ED_P, g * h % ED_P, f * g % ED_P, e * h % ED_P)


def ed_point_double(P):
    """dbl-2008-hwcd."""
    x1, y1, z1, _ = P
    a = x1 * x1 % ED_P
    b = y1 * y1 % ED_P
    c = 2 * z1 * z1 % ED_P
    h = (a + b) % ED_P
    e = (h - (x1 + y1) * (x1 + y1)) % ED_P
    g = (a - b) % ED_P
    f = (c + g) % ED_P
    return (e * f % ED_P, g * h % ED_P, f * g % ED_P, e * h % ED_P)


ED_IDENTITY = (0, 1, 1, 0)


def ed_to_extended(aff):
    x, y = aff
    return (x, y, 1, x * y % ED_P)


def ed_scalar_mul(s: int, P) -> tuple:
    """Double-and-add over extended coords (host path; not constant-time — fine for
    verification and for test fixtures; signing uses it too, acceptable for a
    framework whose threat model matches the reference's dev/sim usage)."""
    Q = ED_IDENTITY
    Pe = P
    while s > 0:
        if s & 1:
            Q = ed_point_add(Q, Pe)
        Pe = ed_point_double(Pe)
        s >>= 1
    return Q


def ed_to_affine(P):
    x, y, z, _ = P
    zi = pow(z, ED_P - 2, ED_P)
    return (x * zi % ED_P, y * zi % ED_P)


def ed_point_compress(aff) -> bytes:
    x, y = aff
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def ed_point_decompress(data: bytes):
    if len(data) != 32:
        return None
    val = int.from_bytes(data, "little")
    sign = val >> 255
    y = val & ((1 << 255) - 1)
    x = _ed_recover_x(y, sign)
    if x is None:
        return None
    return (x, y)


def _sha512_int(*chunks: bytes) -> int:
    h = hashlib.sha512()
    for c in chunks:
        h.update(c)
    return int.from_bytes(h.digest(), "little")


def ed25519_secret_expand(seed: bytes):
    h = hashlib.sha512(seed).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a, h[32:]


def ed25519_public_key(seed: bytes) -> bytes:
    a, _ = ed25519_secret_expand(seed)
    return ed_point_compress(ed_to_affine(ed_scalar_mul(a, ed_to_extended(ED_B))))


def ed25519_sign(seed: bytes, msg: bytes, public: bytes | None = None) -> bytes:
    a, prefix = ed25519_secret_expand(seed)
    A = public if public is not None else ed25519_public_key(seed)
    r = _sha512_int(prefix, msg) % ED_L
    R = ed_point_compress(ed_to_affine(ed_scalar_mul(r, ed_to_extended(ED_B))))
    k = _sha512_int(R, A, msg) % ED_L
    s = (r + k * a) % ED_L
    return R + s.to_bytes(32, "little")


def ed25519_verify(public: bytes, msg: bytes, sig: bytes) -> bool:
    if len(sig) != 64:
        return False
    A = ed_point_decompress(public)
    if A is None:
        return False
    R = ed_point_decompress(sig[:32])
    if R is None:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= ED_L:
        return False
    k = _sha512_int(sig[:32], public, msg) % ED_L
    lhs = ed_scalar_mul(s, ed_to_extended(ED_B))
    rhs = ed_point_add(ed_to_extended(R), ed_scalar_mul(k, ed_to_extended(A)))
    # Projective comparison: x1 z2 == x2 z1 and y1 z2 == y2 z1.
    x1, y1, z1, _ = lhs
    x2, y2, z2, _ = rhs
    return (x1 * z2 - x2 * z1) % ED_P == 0 and (y1 * z2 - y2 * z1) % ED_P == 0


# ---------------------------------------------------------------------------
# Short Weierstrass curves (secp256k1, secp256r1) + ECDSA (SEC 1, RFC 6979)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class WeierstrassCurve:
    name: str
    p: int
    a: int
    b: int
    gx: int
    gy: int
    n: int

    @property
    def g(self):
        return (self.gx, self.gy)

    def is_on_curve(self, P) -> bool:
        if P is None:
            return True
        x, y = P
        return (y * y - x * x * x - self.a * x - self.b) % self.p == 0

    # Affine group law (host oracle path: clarity over speed).
    def add(self, P, Q):
        if P is None:
            return Q
        if Q is None:
            return P
        x1, y1 = P
        x2, y2 = Q
        if x1 == x2 and (y1 + y2) % self.p == 0:
            return None
        if P == Q:
            lam = (3 * x1 * x1 + self.a) * pow(2 * y1, self.p - 2, self.p) % self.p
        else:
            lam = (y2 - y1) * pow(x2 - x1, self.p - 2, self.p) % self.p
        x3 = (lam * lam - x1 - x2) % self.p
        y3 = (lam * (x1 - x3) - y1) % self.p
        return (x3, y3)

    def mul(self, s: int, P):
        R = None
        while s > 0:
            if s & 1:
                R = self.add(R, P)
            P = self.add(P, P)
            s >>= 1
        return R


SECP256K1 = WeierstrassCurve(
    name="secp256k1",
    p=2**256 - 2**32 - 977,
    a=0,
    b=7,
    gx=0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798,
    gy=0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8,
    n=0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141,
)

SECP256R1 = WeierstrassCurve(
    name="secp256r1",
    p=0xFFFFFFFF00000001000000000000000000000000FFFFFFFFFFFFFFFFFFFFFFFF,
    a=0xFFFFFFFF00000001000000000000000000000000FFFFFFFFFFFFFFFFFFFFFFFC,
    b=0x5AC635D8AA3A93E7B3EBBD55769886BC651D06B0CC53B0F63BCE3C3E27D2604B,
    gx=0x6B17D1F2E12C4247F8BCE6E563A440F277037D812DEB33A0F4A13945D898C296,
    gy=0x4FE342E2FE1A7F9B8EE7EB4A7C0F9E162BCE33576B315ECECBB6406837BF51F5,
    n=0xFFFFFFFF00000000FFFFFFFFFFFFFFFFBCE6FAADA7179E84F3B9CAC2FC632551,
)


def _bits2int(data: bytes, n: int) -> int:
    v = int.from_bytes(data, "big")
    blen = len(data) * 8
    nlen = n.bit_length()
    if blen > nlen:
        v >>= blen - nlen
    return v


def rfc6979_nonce(curve: WeierstrassCurve, priv: int, digest: bytes) -> int:
    """Deterministic ECDSA nonce (RFC 6979, HMAC-SHA256)."""
    qlen = (curve.n.bit_length() + 7) // 8
    h1 = _bits2int(digest, curve.n) % curve.n
    x_b = priv.to_bytes(qlen, "big")
    h_b = h1.to_bytes(qlen, "big")
    V = b"\x01" * 32
    K = b"\x00" * 32
    K = hmac.new(K, V + b"\x00" + x_b + h_b, hashlib.sha256).digest()
    V = hmac.new(K, V, hashlib.sha256).digest()
    K = hmac.new(K, V + b"\x01" + x_b + h_b, hashlib.sha256).digest()
    V = hmac.new(K, V, hashlib.sha256).digest()
    while True:
        t = b""
        while len(t) < qlen:
            V = hmac.new(K, V, hashlib.sha256).digest()
            t += V
        k = _bits2int(t[:qlen], curve.n)
        if 1 <= k < curve.n:
            return k
        K = hmac.new(K, V + b"\x00", hashlib.sha256).digest()
        V = hmac.new(K, V, hashlib.sha256).digest()


def ecdsa_sign(curve: WeierstrassCurve, priv: int, msg: bytes) -> tuple[int, int]:
    """Sign SHA-256(msg); returns (r, s) with low-s normalisation."""
    digest = hashlib.sha256(msg).digest()
    e = _bits2int(digest, curve.n) % curve.n
    while True:
        k = rfc6979_nonce(curve, priv, digest)
        R = curve.mul(k, curve.g)
        r = R[0] % curve.n
        if r == 0:
            continue
        s = (e + r * priv) * pow(k, curve.n - 2, curve.n) % curve.n
        if s == 0:
            continue
        if s > curve.n // 2:
            s = curve.n - s
        return r, s


def ecdsa_verify(curve: WeierstrassCurve, pub, msg: bytes, r: int, s: int) -> bool:
    # Low-s only (matching the signer's normalisation): rejects the s' = n - s
    # malleated twin so each message/key pair has exactly one accepted signature.
    if not (1 <= r < curve.n and 1 <= s <= curve.n // 2):
        return False
    if pub is None or not curve.is_on_curve(pub):
        return False
    digest = hashlib.sha256(msg).digest()
    e = _bits2int(digest, curve.n) % curve.n
    w = pow(s, curve.n - 2, curve.n)
    u1 = e * w % curve.n
    u2 = r * w % curve.n
    X = curve.add(curve.mul(u1, curve.g), curve.mul(u2, pub))
    if X is None:
        return False
    return X[0] % curve.n == r


# -- DER encoding of ECDSA signatures (interop with the `cryptography` oracle) --

def _der_int(v: int) -> bytes:
    b = v.to_bytes((v.bit_length() + 7) // 8 or 1, "big")
    if b[0] & 0x80:
        b = b"\x00" + b
    return b"\x02" + bytes([len(b)]) + b


def ecdsa_sig_to_der(r: int, s: int) -> bytes:
    body = _der_int(r) + _der_int(s)
    return b"\x30" + bytes([len(body)]) + body


def ecdsa_sig_from_der(data: bytes) -> tuple[int, int]:
    """Strict DER (r, s) parse: rejects truncated input, bad tags, and trailing
    garbage, so every (r, s) has exactly one accepted encoding (no malleability
    via re-encoding). Raises ValueError on any malformation."""
    if len(data) < 8 or data[0] != 0x30:
        raise ValueError("bad DER signature")
    if data[1] != len(data) - 2:
        raise ValueError("bad DER signature length")
    idx = 2

    def read_int(i):
        if i + 2 > len(data) or data[i] != 0x02:
            raise ValueError("bad DER integer")
        ln = data[i + 1]
        if ln == 0 or i + 2 + ln > len(data):
            raise ValueError("bad DER integer length")
        body = data[i + 2:i + 2 + ln]
        if body[0] & 0x80:
            raise ValueError("negative DER integer")
        if ln > 1 and body[0] == 0 and not (body[1] & 0x80):
            raise ValueError("non-minimal DER integer")
        return int.from_bytes(body, "big"), i + 2 + ln

    r, idx = read_int(idx)
    s, idx = read_int(idx)
    if idx != len(data):
        raise ValueError("trailing bytes after DER signature")
    return r, s


# ---------------------------------------------------------------------------
# GLV endomorphism for secp256k1 (verification speed: halves ladder length)
# ---------------------------------------------------------------------------
# secp256k1 has an efficient endomorphism phi(x, y) = (beta*x, y) = [lambda]P
# (j-invariant 0 curve). Scalars split as k = k1 + k2*lambda (mod n) with
# |k1|, |k2| < 2^128 via the standard lattice basis (GLV 2001; the constants
# are the well-known public secp256k1 values). Used by the device ECDSA kernel
# to run a 4-scalar 129-bit Shamir ladder instead of a 2-scalar 256-bit one.

SECP256K1_LAMBDA = 0x5363AD4CC05C30E0A5261C028812645A122E22EA20816678DF02967C1B23BD72
SECP256K1_BETA = 0x7AE96A2B657C07106E64479EAC3434E99CF0497512F58995C1396C28719501EE
_GLV_A1 = 0x3086D221A7D46BCDE86C90E49284EB15
_GLV_B1 = -0xE4437ED6010E88286F547FA90ABFE4C3
_GLV_A2 = 0x114CA50F7A8E2F3F657C1108D9D44CFD8
_GLV_B2 = _GLV_A1


def glv_decompose(k: int) -> tuple[int, int]:
    """k (mod n) -> (k1, k2), signed, |k1|,|k2| < 2^128, with
    k1 + k2*lambda == k (mod n)."""
    n = SECP256K1.n
    c1 = (_GLV_B2 * k + n // 2) // n
    c2 = (-_GLV_B1 * k + n // 2) // n
    k1 = k - c1 * _GLV_A1 - c2 * _GLV_A2
    k2 = -c1 * _GLV_B1 - c2 * _GLV_B2
    return k1, k2
