"""Merkle trees and partial (tear-off) Merkle proofs — host semantics.

Reference parity: MerkleTree.kt:27-66 (bottom-up build, leaf list zero-padded to the
next power of two, node hash = single SHA-256 of the 64-byte concatenation) and
PartialMerkleTree.kt (tear-off proofs used by FilteredTransaction and oracles).

The batched device implementation (leaf hashing + level reduction as JAX kernels,
cross-chip combine via collectives) lives in ``corda_tpu.ops.sha256``
(``merkle_root``; sharded variant ``corda_tpu.parallel.sharded``) and is tested
bit-exact against this module.
"""
from __future__ import annotations

from dataclasses import dataclass

from .secure_hash import SecureHash


class MerkleTreeException(Exception):
    pass


@dataclass(frozen=True)
class MerkleTree:
    """A full binary Merkle tree node (leaves are trees with no children)."""

    hash: SecureHash
    left: "MerkleTree | None" = None
    right: "MerkleTree | None" = None

    @property
    def is_leaf(self) -> bool:
        return self.left is None

    @staticmethod
    def get_merkle_tree(all_leaves_hashes: list[SecureHash]) -> "MerkleTree":
        """Build bottom-up; pad the leaf level with zero-hashes to a power of two."""
        if not all_leaves_hashes:
            raise MerkleTreeException("Cannot calculate Merkle root on empty hash list.")
        leaves = pad_to_power_of_two(all_leaves_hashes)
        level = [MerkleTree(h) for h in leaves]
        while len(level) > 1:
            nxt = []
            for i in range(0, len(level), 2):
                l, r = level[i], level[i + 1]
                nxt.append(MerkleTree(l.hash.hash_concat(r.hash), l, r))
            level = nxt
        return level[0]

    @staticmethod
    def root_hash(all_leaves_hashes: list[SecureHash]) -> SecureHash:
        return MerkleTree.get_merkle_tree(all_leaves_hashes).hash


def pad_to_power_of_two(hashes: list[SecureHash]) -> list[SecureHash]:
    n = 1
    while n < len(hashes):
        n <<= 1
    return list(hashes) + [SecureHash.zero_hash()] * (n - len(hashes))


# ---------------------------------------------------------------------------
# Partial Merkle trees (tear-offs)
# ---------------------------------------------------------------------------

# Proof-tree nodes: exactly one of the reference's PartialTree variants.
@dataclass(frozen=True)
class _IncludedLeaf:
    hash: SecureHash


@dataclass(frozen=True)
class _Leaf:
    hash: SecureHash


@dataclass(frozen=True)
class _Node:
    left: "PartialTree"
    right: "PartialTree"


PartialTree = _IncludedLeaf | _Leaf | _Node


@dataclass(frozen=True)
class PartialMerkleTree:
    """A pruned Merkle tree revealing only the included leaves plus the minimal set
    of sibling hashes needed to recompute the root."""

    root: PartialTree

    @staticmethod
    def build(merkle_tree: MerkleTree, included_hashes: list[SecureHash]) -> "PartialMerkleTree":
        used: set[SecureHash] = set()
        tree = _prune(merkle_tree, set(included_hashes), used)
        missing = set(included_hashes) - used
        if missing:
            raise MerkleTreeException(
                f"Some of the provided hashes are not in the tree: {missing}")
        return PartialMerkleTree(tree)

    def verify(self, expected_root: SecureHash, hashes_to_check: list[SecureHash]) -> bool:
        root_hash, included = _rebuild(self.root)
        return root_hash == expected_root and set(hashes_to_check) == set(included)

    @property
    def included_hashes(self) -> list[SecureHash]:
        return _rebuild(self.root)[1]


def _prune(tree: MerkleTree, include: set[SecureHash], used: set[SecureHash]) -> PartialTree:
    if tree.is_leaf:
        if tree.hash in include:
            used.add(tree.hash)
            return _IncludedLeaf(tree.hash)
        return _Leaf(tree.hash)
    left = _prune(tree.left, include, used)
    right = _prune(tree.right, include, used)
    if isinstance(left, _Leaf) and isinstance(right, _Leaf):
        return _Leaf(tree.hash)  # collapse fully-hidden subtrees to one hash
    return _Node(left, right)


def _rebuild(node: PartialTree) -> tuple[SecureHash, list[SecureHash]]:
    if isinstance(node, _IncludedLeaf):
        return node.hash, [node.hash]
    if isinstance(node, _Leaf):
        return node.hash, []
    lh, li = _rebuild(node.left)
    rh, ri = _rebuild(node.right)
    return lh.hash_concat(rh), li + ri
