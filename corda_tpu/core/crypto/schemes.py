"""Pluggable signature schemes.

Reference parity: Crypto.kt:77-165 — five schemes (RSA_SHA256, ECDSA_SECP256K1_SHA256,
ECDSA_SECP256R1_SHA256, EDDSA_ED25519_SHA512 (default), SPHINCS256_SHA256) plus the
COMPOSITE pseudo-scheme. Scheme numbers match the reference so serialized scheme ids
line up across implementations.
"""
from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class SignatureScheme:
    scheme_number_id: int
    scheme_code_name: str
    algorithm_name: str
    key_size: int | None
    description: str

    def __str__(self) -> str:
        return self.scheme_code_name


RSA_SHA256 = SignatureScheme(1, "RSA_SHA256", "RSA", 3072, "RSA PKCS#1 v1.5 with SHA-256")
ECDSA_SECP256K1_SHA256 = SignatureScheme(2, "ECDSA_SECP256K1_SHA256", "ECDSA", 256, "ECDSA over secp256k1 with SHA-256")
ECDSA_SECP256R1_SHA256 = SignatureScheme(3, "ECDSA_SECP256R1_SHA256", "ECDSA", 256, "ECDSA over secp256r1 (NIST P-256) with SHA-256")
EDDSA_ED25519_SHA512 = SignatureScheme(4, "EDDSA_ED25519_SHA512", "EdDSA", 256, "Ed25519 (RFC 8032) with SHA-512")
SPHINCS256_SHA256 = SignatureScheme(5, "SPHINCS-256_SHA512_256", "SPHINCS256", 256, "SPHINCS-256 hash-based signature (post-quantum)")
COMPOSITE_KEY = SignatureScheme(6, "COMPOSITE", "COMPOSITE", None, "Weighted-threshold composite key of other schemes")

ALL_SCHEMES = (RSA_SHA256, ECDSA_SECP256K1_SHA256, ECDSA_SECP256R1_SHA256,
               EDDSA_ED25519_SHA512, SPHINCS256_SHA256, COMPOSITE_KEY)

#: Default scheme, as in the reference (Crypto.kt:170).
DEFAULT_SIGNATURE_SCHEME = EDDSA_ED25519_SHA512

_BY_ID = {s.scheme_number_id: s for s in ALL_SCHEMES}
_BY_NAME = {s.scheme_code_name: s for s in ALL_SCHEMES}


def scheme_by_id(num: int) -> SignatureScheme:
    try:
        return _BY_ID[num]
    except KeyError:
        raise ValueError(f"Unsupported signature scheme id {num}")


def scheme_by_name(name: str) -> SignatureScheme:
    try:
        return _BY_NAME[name]
    except KeyError:
        raise ValueError(f"Unsupported signature scheme {name!r}")
