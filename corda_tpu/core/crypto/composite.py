"""Weighted-threshold composite keys.

Reference parity: core/.../crypto/composite/CompositeKey.kt:35 — a ``PublicKey``
implementation that is a tree of (child key, weight) nodes with a per-node threshold.
A composite key is fulfilled by a set of leaf keys iff the sum of the weights of the
fulfilled children reaches the threshold, recursively.

The TPU verification pipeline evaluates composite thresholds on the HOST over the
batch of per-leaf device verdicts (SURVEY.md §7 phase 1): the device returns one
bool per (key, sig, msg) triple; this module folds them through the key tree.
"""
from __future__ import annotations

import struct
from dataclasses import dataclass

from .keys import PublicKey
from .schemes import COMPOSITE_KEY


@dataclass(frozen=True)
class NodeAndWeight:
    node: PublicKey  # leaf key or nested CompositeKey
    weight: int


class CompositeKey(PublicKey):
    """Immutable weighted-threshold key tree. Equality via canonical encoding."""

    __slots__ = ("threshold", "children")

    def __init__(self, threshold: int, children: tuple[NodeAndWeight, ...]):
        children = tuple(sorted(children, key=lambda nw: (nw.node.scheme.scheme_number_id,
                                                          nw.node.encoded)))
        self.threshold = threshold
        self.children = children
        super().__init__(COMPOSITE_KEY, self._encode())
        self._validate()

    # -- construction --------------------------------------------------------
    class Builder:
        def __init__(self):
            self._children: list[NodeAndWeight] = []

        def add_key(self, key: PublicKey, weight: int = 1) -> "CompositeKey.Builder":
            self._children.append(NodeAndWeight(key, weight))
            return self

        def add_keys(self, *keys: PublicKey) -> "CompositeKey.Builder":
            for k in keys:
                self.add_key(k)
            return self

        def build(self, threshold: int | None = None) -> PublicKey:
            n = len(self._children)
            if n == 0:
                raise ValueError("Cannot build CompositeKey with zero children")
            if n == 1 and threshold in (None, self._children[0].weight):
                # Collapsing single-child trees mirrors the reference builder.
                return self._children[0].node
            t = threshold if threshold is not None else sum(c.weight for c in self._children)
            return CompositeKey(t, tuple(self._children))

    def _validate(self):
        if self.threshold <= 0:
            raise ValueError("CompositeKey threshold must be positive")
        total = 0
        seen = set()
        for c in self.children:
            if c.weight <= 0:
                raise ValueError("CompositeKey child weights must be positive")
            if c.node in seen:
                raise ValueError("CompositeKey must not contain duplicate child keys")
            seen.add(c.node)
            total += c.weight
        if self.threshold > total:
            raise ValueError("CompositeKey threshold exceeds sum of weights")
        # No cycle check needed: trees are built bottom-up from immutable by-value
        # nodes, so a node can never contain itself (unlike the reference's
        # by-reference Java object graphs, CompositeKey.kt cycle detection).

    def _encode(self) -> bytes:
        parts = [struct.pack(">BI H", 0xC0, self.threshold, len(self.children))]
        for c in self.children:
            enc = c.node.encoded
            parts.append(struct.pack(">I B I", c.weight,
                                     c.node.scheme.scheme_number_id, len(enc)))
            parts.append(enc)
        return b"".join(parts)

    @staticmethod
    def decode(data: bytes) -> "CompositeKey":
        """Strict decode: bounds-checked, full-consumption (rejects trailing bytes)
        so each key has exactly one accepted encoding."""
        from .schemes import scheme_by_id
        try:
            tag, threshold, n = struct.unpack_from(">BI H", data, 0)
        except struct.error:
            raise ValueError("Truncated composite key encoding")
        if tag != 0xC0:
            raise ValueError("Not a composite key encoding")
        off = struct.calcsize(">BI H")
        hdr = struct.calcsize(">I B I")
        children = []
        for _ in range(n):
            try:
                weight, sid, ln = struct.unpack_from(">I B I", data, off)
            except struct.error:
                raise ValueError("Truncated composite key child header")
            off += hdr
            if off + ln > len(data):
                raise ValueError("Composite key child length exceeds buffer")
            enc = data[off:off + ln]
            off += ln
            if sid == COMPOSITE_KEY.scheme_number_id:
                child: PublicKey = CompositeKey.decode(enc)
            else:
                child = PublicKey(scheme_by_id(sid), enc)
            children.append(NodeAndWeight(child, weight))
        if off != len(data):
            raise ValueError("Trailing bytes after composite key encoding")
        return CompositeKey(threshold, tuple(children))

    # -- fulfilment ----------------------------------------------------------
    @property
    def keys(self) -> frozenset[PublicKey]:
        out: set[PublicKey] = set()
        for c in self.children:
            out |= c.node.keys
        return frozenset(out)

    def is_fulfilled_by(self, keys) -> bool:
        if isinstance(keys, PublicKey):
            keys = (keys,)
        key_set = set(keys)
        total = 0
        for c in self.children:
            ok = (c.node.is_fulfilled_by(key_set) if isinstance(c.node, CompositeKey)
                  else c.node in key_set)
            if ok:
                total += c.weight
                if total >= self.threshold:
                    return True
        return False

    def __repr__(self):
        return f"CompositeKey(threshold={self.threshold}, children={len(self.children)})"


@dataclass(frozen=True)
class CompositeSignaturesWithKeys:
    """A bundle of leaf signatures intended to satisfy a composite key."""

    sigs: tuple  # tuple[DigitalSignatureWithKey, ...]


class CompositeSignature:
    """Verification of a composite key from leaf signatures: every provided leaf
    signature must itself verify, and the fulfilled leaves must reach the threshold."""

    @staticmethod
    def verify(composite: CompositeKey, content: bytes, sigs: CompositeSignaturesWithKeys) -> bool:
        valid_keys = set()
        for sig in sigs.sigs:
            if sig.is_valid(content):
                valid_keys.add(sig.by)
        return composite.is_fulfilled_by(valid_keys)
