"""Host-side cryptography: hashing, signature schemes, composite keys, Merkle trees.

The device (TPU) implementations of the hot paths live in ``corda_tpu.ops``; this
package is the authoritative host semantics they are tested bit-exact against.

Reference parity: core/src/main/kotlin/net/corda/core/crypto (Crypto.kt, SecureHash.kt,
MerkleTree.kt, PartialMerkleTree.kt, composite/CompositeKey.kt).
"""
from .secure_hash import SecureHash, sha256, sha256_twice, hash_concat
from .schemes import (
    SignatureScheme,
    EDDSA_ED25519_SHA512,
    ECDSA_SECP256K1_SHA256,
    ECDSA_SECP256R1_SHA256,
    RSA_SHA256,
    SPHINCS256_SHA256,
    COMPOSITE_KEY,
    ALL_SCHEMES,
    DEFAULT_SIGNATURE_SCHEME,
    scheme_by_id,
)
from .keys import PublicKey, PrivateKey, KeyPair, generate_keypair
from .signatures import DigitalSignature, TransactionSignature, Crypto
from .composite import CompositeKey, CompositeSignature, CompositeSignaturesWithKeys
from .merkle import MerkleTree, PartialMerkleTree, MerkleTreeException
from .base58 import b58encode, b58decode

__all__ = [
    "SecureHash", "sha256", "sha256_twice", "hash_concat",
    "SignatureScheme", "EDDSA_ED25519_SHA512", "ECDSA_SECP256K1_SHA256",
    "ECDSA_SECP256R1_SHA256", "RSA_SHA256", "SPHINCS256_SHA256", "COMPOSITE_KEY",
    "ALL_SCHEMES", "DEFAULT_SIGNATURE_SCHEME", "scheme_by_id",
    "PublicKey", "PrivateKey", "KeyPair", "generate_keypair",
    "DigitalSignature", "TransactionSignature", "Crypto",
    "CompositeKey", "CompositeSignature", "CompositeSignaturesWithKeys",
    "MerkleTree", "PartialMerkleTree", "MerkleTreeException",
    "b58encode", "b58decode",
]
