"""Content hashes.

Reference parity: core/src/main/kotlin/net/corda/core/crypto/SecureHash.kt.
Notably the Merkle path uses a *single* SHA-256 for both leaf and node hashes
(SecureHash.kt:24,36 — ``sha256Twice`` exists but is unused by MerkleTree).
"""
from __future__ import annotations

import hashlib
import os
from dataclasses import dataclass


@dataclass(frozen=True, order=True)
class SecureHash:
    """An immutable 32-byte SHA-256 content hash."""

    bytes: bytes

    SIZE = 32

    def __post_init__(self):
        if len(self.bytes) != self.SIZE:
            raise ValueError(f"SecureHash must be {self.SIZE} bytes, got {len(self.bytes)}")

    # -- constructors -------------------------------------------------------
    @staticmethod
    def sha256(data: bytes) -> "SecureHash":
        return SecureHash(hashlib.sha256(data).digest())

    @staticmethod
    def sha256_twice(data: bytes) -> "SecureHash":
        return SecureHash.sha256(hashlib.sha256(data).digest())

    @staticmethod
    def parse(hex_str: str) -> "SecureHash":
        return SecureHash(bytes.fromhex(hex_str))

    @staticmethod
    def random_sha256() -> "SecureHash":
        return SecureHash.sha256(os.urandom(32))

    @staticmethod
    def zero_hash() -> "SecureHash":
        return SecureHash(b"\x00" * SecureHash.SIZE)

    @staticmethod
    def all_ones_hash() -> "SecureHash":
        return SecureHash(b"\xff" * SecureHash.SIZE)

    # -- combinators --------------------------------------------------------
    def hash_concat(self, other: "SecureHash") -> "SecureHash":
        """Merkle node combine: single SHA-256 of the 64-byte concatenation."""
        return SecureHash.sha256(self.bytes + other.bytes)

    def re_hash(self) -> "SecureHash":
        return SecureHash.sha256(self.bytes)

    # -- misc ---------------------------------------------------------------
    def hex(self) -> str:
        return self.bytes.hex()

    def prefix_chars(self, n: int = 6) -> str:
        return self.hex()[:n].upper()

    def __str__(self) -> str:
        return self.hex().upper()

    def __repr__(self) -> str:
        return f"SecureHash({self.hex()[:16]}…)"

    def __hash__(self) -> int:
        return int.from_bytes(self.bytes[:8], "big")


def sha256(data: bytes) -> SecureHash:
    return SecureHash.sha256(data)


def sha256_twice(data: bytes) -> SecureHash:
    return SecureHash.sha256_twice(data)


def hash_concat(left: SecureHash, right: SecureHash) -> SecureHash:
    return left.hash_concat(right)
