"""Signing and verification dispatch across schemes — the ``Crypto`` facade.

Reference parity: Crypto.kt doSign (:368-432), doVerify (:438-511), isValid (:518-544);
DigitalSignature.WithKey (DigitalSignature.kt:25); CryptoUtils.kt:49.

The hot path in production is NOT this module: batched verification runs on TPU via
``corda_tpu.ops`` / the verifier service. This host path is the semantic oracle, the
signing path, and the fallback for schemes with no device kernel (RSA).
"""
from __future__ import annotations

import functools
import hashlib
from dataclasses import dataclass

from . import ecmath
from .keys import PublicKey, PrivateKey, KeyPair, curve_for_scheme, sec1_decompress
from .schemes import (
    SignatureScheme, RSA_SHA256, ECDSA_SECP256K1_SHA256, ECDSA_SECP256R1_SHA256,
    EDDSA_ED25519_SHA512, SPHINCS256_SHA256,
)


class SignatureException(Exception):
    pass


@dataclass(frozen=True)
class DigitalSignature:
    """A raw signature (scheme-specific encoding: Ed25519 = 64-byte RFC 8032;
    ECDSA = DER (r,s); RSA = PKCS#1 block)."""

    bytes: bytes

    def __hash__(self):
        return hash(self.bytes)


@dataclass(frozen=True)
class DigitalSignatureWithKey(DigitalSignature):
    """A signature bundled with the verification key (DigitalSignature.WithKey)."""

    by: PublicKey

    def verify(self, content: bytes) -> bool:
        """Raise on invalid signature; return True on success (doVerify semantics)."""
        return Crypto.do_verify(self.by, self.bytes, content)

    def is_valid(self, content: bytes) -> bool:
        """Non-throwing validity check (isValid semantics)."""
        return Crypto.is_valid(self.by, self.bytes, content)

    def without_key(self) -> DigitalSignature:
        return DigitalSignature(self.bytes)

    def __hash__(self):
        return hash((self.bytes, self.by))


# Alias matching the transaction-layer naming.
TransactionSignature = DigitalSignatureWithKey


def _openssl_ecdsa_verify(scheme_id: int, encoded: bytes, content: bytes,
                          r: int, s: int):
    """OpenSSL-backed ECDSA curve-equation check, or None when the
    ``cryptography`` package is unavailable. Policy (ranges, low-s, curve
    membership, DER canonicalisation) is enforced by the CALLER; the (r, s)
    pair is re-encoded to canonical DER here so OpenSSL never sees the
    caller's encoding quirks."""
    try:
        key = _openssl_key(scheme_id, encoded)
    except Exception:
        return None
    if key is None:
        return None
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec
    try:
        key.verify(ecmath.ecdsa_sig_to_der(r, s), content,
                   ec.ECDSA(hashes.SHA256()))
        return True
    except InvalidSignature:
        return False


def _openssl_ed25519_verify(encoded: bytes, content: bytes, signature: bytes):
    """OpenSSL-backed Ed25519 equation check, or None when unavailable.
    Structural policy is enforced by the CALLER with our own decoder."""
    try:
        key = _openssl_ed_key(encoded)
    except Exception:
        return None
    if key is None:
        return None
    from cryptography.exceptions import InvalidSignature
    try:
        key.verify(signature, content)
        return True
    except InvalidSignature:
        return False


@functools.lru_cache(maxsize=65536)
def _openssl_ed_key(encoded: bytes):
    try:
        from cryptography.hazmat.primitives.asymmetric import ed25519
    except ImportError:
        return None
    return ed25519.Ed25519PublicKey.from_public_bytes(encoded)


@functools.lru_cache(maxsize=65536)
def _openssl_key(scheme_id: int, encoded: bytes):
    """Decode + cache an OpenSSL EC public key object per encoding (the
    point decompression is the expensive part and keys repeat heavily)."""
    try:
        from cryptography.hazmat.primitives.asymmetric import ec
    except ImportError:
        return None
    curve_obj = (ec.SECP256K1()
                 if scheme_id == ECDSA_SECP256K1_SHA256.scheme_number_id
                 else ec.SECP256R1())
    return ec.EllipticCurvePublicKey.from_encoded_point(curve_obj, encoded)


class Crypto:
    """Scheme dispatch (mirror of the reference ``Crypto`` object)."""

    @staticmethod
    def do_sign(private: PrivateKey, content: bytes,
                public: PublicKey | None = None) -> bytes:
        sid = private.scheme.scheme_number_id
        if sid == EDDSA_ED25519_SHA512.scheme_number_id:
            pub_bytes = public.encoded if public is not None else None
            return ecmath.ed25519_sign(private.encoded, content, public=pub_bytes)
        if sid in (ECDSA_SECP256K1_SHA256.scheme_number_id,
                   ECDSA_SECP256R1_SHA256.scheme_number_id):
            curve = curve_for_scheme(private.scheme)
            d = int.from_bytes(private.encoded, "big")
            r, s = ecmath.ecdsa_sign(curve, d, content)
            return ecmath.ecdsa_sig_to_der(r, s)
        if sid == RSA_SHA256.scheme_number_id:
            from cryptography.hazmat.primitives.asymmetric import padding
            from cryptography.hazmat.primitives import hashes, serialization
            key = serialization.load_der_private_key(private.encoded, password=None)
            return key.sign(content, padding.PKCS1v15(), hashes.SHA256())
        if sid == SPHINCS256_SHA256.scheme_number_id:
            from . import sphincs
            return sphincs.sign(private.encoded, content)
        raise SignatureException(f"Unsupported scheme for signing: {private.scheme}")

    @staticmethod
    def sign_with_key(keypair_or_private, content: bytes, public: PublicKey | None = None
                      ) -> DigitalSignatureWithKey:
        if isinstance(keypair_or_private, KeyPair):
            private, public = keypair_or_private.private, keypair_or_private.public
        else:
            private = keypair_or_private
            if public is None:
                raise ValueError("public key required when signing with a bare private key")
        return DigitalSignatureWithKey(Crypto.do_sign(private, content, public), public)

    @staticmethod
    def is_valid(public: PublicKey, signature: bytes, content: bytes) -> bool:
        sid = public.scheme.scheme_number_id
        if sid == EDDSA_ED25519_SHA512.scheme_number_id:
            # structural policy (canonical point decodes, s < L) decided by
            # OUR decoder — identical to the device kernel precheck; the
            # verification equation itself then rides OpenSSL when present
            # (RFC 8032 cofactorless, same equation as ecmath/kernels)
            if (len(signature) != 64
                    or ecmath.ed_point_decompress(public.encoded) is None
                    or ecmath.ed_point_decompress(signature[:32]) is None
                    or int.from_bytes(signature[32:], "little") >= ecmath.ED_L):
                return False
            fast = _openssl_ed25519_verify(public.encoded, content, signature)
            if fast is not None:
                return fast
            return ecmath.ed25519_verify(public.encoded, content, signature)
        if sid in (ECDSA_SECP256K1_SHA256.scheme_number_id,
                   ECDSA_SECP256R1_SHA256.scheme_number_id):
            curve = curve_for_scheme(public.scheme)
            point = sec1_decompress(curve, public.encoded)
            if point is None:
                return False
            try:
                r, s = ecmath.ecdsa_sig_from_der(signature)
            except (ValueError, IndexError):
                return False
            # The acceptance POLICY (ranges incl. low-s, on-curve key,
            # canonical DER) is decided above/by ecdsa_verify's prechecks —
            # identically to the device kernels' precheck. Once policy
            # passes, the curve-equation check itself is implementation-
            # independent, so the host path may ride OpenSSL (~100x the
            # pure-Python ladder; this is the batcher's sub-crossover /
            # p50@batch=1 path) with the pure ladder as fallback oracle.
            if not (1 <= r < curve.n and 1 <= s <= curve.n // 2):
                return False
            fast = _openssl_ecdsa_verify(public.scheme.scheme_number_id,
                                         public.encoded, content, r, s)
            if fast is not None:
                return fast
            return ecmath.ecdsa_verify(curve, point, content, r, s)
        if sid == RSA_SHA256.scheme_number_id:
            from cryptography.hazmat.primitives.asymmetric import padding
            from cryptography.hazmat.primitives import hashes, serialization
            from cryptography.exceptions import InvalidSignature
            key = serialization.load_der_public_key(public.encoded)
            try:
                key.verify(signature, content, padding.PKCS1v15(), hashes.SHA256())
                return True
            except InvalidSignature:
                return False
        if sid == SPHINCS256_SHA256.scheme_number_id:
            from . import sphincs
            return sphincs.verify(public.encoded, content, signature)
        raise SignatureException(f"Unsupported scheme for verification: {public.scheme}")

    @staticmethod
    def do_verify(public: PublicKey, signature: bytes, content: bytes) -> bool:
        """Throwing verify (doVerify semantics, Crypto.kt:438-511)."""
        if not content:
            raise SignatureException("Signing of an empty array is not permitted")
        if not Crypto.is_valid(public, signature, content):
            raise SignatureException(
                f"Signature by {public.to_string_short()} did not verify")
        return True


def sha256_digest(content: bytes) -> bytes:
    return hashlib.sha256(content).digest()
