"""Key material for the pluggable signature schemes.

Key encodings are raw, deterministic and scheme-specific (not ASN.1/X.509 — the
canonical codec in ``core.serialization`` frames them):

- Ed25519: 32-byte compressed point (RFC 8032) / 32-byte seed.
- ECDSA (both curves): 33-byte SEC1 compressed point / 32-byte big-endian scalar.
- RSA: DER SubjectPublicKeyInfo / PKCS#8 (delegated to the ``cryptography`` library).

Reference parity: Crypto.kt key generation + key classes; CryptoUtils.kt helpers
(``toStringShort`` = "DL" + base58(sha256(encoded))).
"""
from __future__ import annotations

import functools
import os
from dataclasses import dataclass, field
from functools import total_ordering

from . import ecmath
from .base58 import b58encode
from .secure_hash import SecureHash
from .schemes import (
    SignatureScheme, RSA_SHA256, ECDSA_SECP256K1_SHA256, ECDSA_SECP256R1_SHA256,
    EDDSA_ED25519_SHA512, SPHINCS256_SHA256, DEFAULT_SIGNATURE_SCHEME,
)


@total_ordering
class PublicKey:
    """Base of all verification keys, including :class:`CompositeKey`.

    Equality/hash are over (scheme id, encoded bytes) so keys can be used as dict keys
    and set members everywhere the reference uses ``java.security.PublicKey``.
    """

    __slots__ = ("scheme", "encoded")

    def __init__(self, scheme: SignatureScheme, encoded: bytes):
        self.scheme = scheme
        self.encoded = bytes(encoded)

    # -- composite-key compatible surface (CryptoUtils.kt) -------------------
    @property
    def keys(self) -> frozenset["PublicKey"]:
        """The set of leaf keys: for a plain key, itself."""
        return frozenset((self,))

    def is_fulfilled_by(self, keys) -> bool:
        if isinstance(keys, PublicKey):
            keys = (keys,)
        return self in set(keys)

    def contains_any(self, other_keys) -> bool:
        return not self.keys.isdisjoint(set(other_keys))

    # -- identity ------------------------------------------------------------
    def to_string_short(self) -> str:
        return "DL" + b58encode(SecureHash.sha256(self.encoded).bytes)

    def __eq__(self, other):
        return (isinstance(other, PublicKey)
                and self.scheme.scheme_number_id == other.scheme.scheme_number_id
                and self.encoded == other.encoded)

    def __lt__(self, other):
        return (self.scheme.scheme_number_id, self.encoded) < (
            other.scheme.scheme_number_id, other.encoded)

    def __hash__(self):
        return hash((self.scheme.scheme_number_id, self.encoded))

    def __repr__(self):
        return f"PublicKey({self.scheme.scheme_code_name}, {self.to_string_short()[:14]}…)"


@dataclass(frozen=True)
class PrivateKey:
    scheme: SignatureScheme
    encoded: bytes = field(repr=False)

    def __hash__(self):
        return hash((self.scheme.scheme_number_id, self.encoded))


@dataclass(frozen=True)
class KeyPair:
    public: PublicKey
    private: PrivateKey


# ---------------------------------------------------------------------------
# SEC1 point encoding for the ECDSA curves
# ---------------------------------------------------------------------------

def sec1_compress(curve: ecmath.WeierstrassCurve, point) -> bytes:
    x, y = point
    return bytes([2 + (y & 1)]) + x.to_bytes(32, "big")


def sec1_decompress_cached(curve: ecmath.WeierstrassCurve, data: bytes):
    """sec1_decompress with the modular square root memoized per (curve,
    encoding). Decompression costs a 256-bit modpow; verification workloads
    see the same signer keys over and over (per-party keys across a ledger),
    so the batcher's host prep rides this cache."""
    return _decompress_lru(curve.name, data)


@functools.lru_cache(maxsize=65536)
def _decompress_lru(curve_name: str, data: bytes):
    curve = (ecmath.SECP256K1 if curve_name == "secp256k1"
             else ecmath.SECP256R1)
    return sec1_decompress(curve, data)


def sec1_pub_row_cached(curve: ecmath.WeierstrassCurve, data: bytes):
    """``sec1_decompress_cached`` in the native preps' wire format: the (8,)
    little-endian u64 row (x ‖ y, 32 LE bytes each) that sm_k1_prep /
    sm_r1_prep consume. Memoized per (curve, encoding) — the batcher's ECDSA
    prep copies one cached row per item instead of paying decompress plus
    two ``to_bytes`` round trips (the Weierstrass analog of the Ed25519
    kernel's per-signer A′ row cache). Returns None for invalid encodings."""
    return _pub_row_lru(curve.name, bytes(data))


@functools.lru_cache(maxsize=65536)
def _pub_row_lru(curve_name: str, data: bytes):
    import numpy as np
    pt = _decompress_lru(curve_name, data)
    if pt is None:
        return None
    # frombuffer over bytes is read-only — safe to share across batches
    return np.frombuffer(pt[0].to_bytes(32, "little")
                         + pt[1].to_bytes(32, "little"), dtype="<u8")


def sec1_decompress(curve: ecmath.WeierstrassCurve, data: bytes):
    if len(data) == 65 and data[0] == 4:
        x = int.from_bytes(data[1:33], "big")
        y = int.from_bytes(data[33:], "big")
        return (x, y) if curve.is_on_curve((x, y)) else None
    if len(data) != 33 or data[0] not in (2, 3):
        return None
    x = int.from_bytes(data[1:], "big")
    if x >= curve.p:
        return None
    y2 = (pow(x, 3, curve.p) + curve.a * x + curve.b) % curve.p
    y = pow(y2, (curve.p + 1) // 4, curve.p)  # p ≡ 3 (mod 4) for both curves
    if y * y % curve.p != y2:
        return None
    if (y & 1) != (data[0] & 1):
        y = curve.p - y
    return (x, y)


_ECDSA_CURVES = {
    ECDSA_SECP256K1_SHA256.scheme_number_id: ecmath.SECP256K1,
    ECDSA_SECP256R1_SHA256.scheme_number_id: ecmath.SECP256R1,
}


def curve_for_scheme(scheme: SignatureScheme) -> ecmath.WeierstrassCurve:
    return _ECDSA_CURVES[scheme.scheme_number_id]


# ---------------------------------------------------------------------------
# Key generation
# ---------------------------------------------------------------------------

def generate_keypair(scheme: SignatureScheme = DEFAULT_SIGNATURE_SCHEME,
                     entropy: bytes | None = None) -> KeyPair:
    """Generate a key pair. ``entropy`` (32 bytes) makes generation deterministic —
    used by tests and by the deterministic ledger generator (GeneratedLedger parity).
    """
    sid = scheme.scheme_number_id
    if sid == EDDSA_ED25519_SHA512.scheme_number_id:
        seed = entropy if entropy is not None else os.urandom(32)
        pub = ecmath.ed25519_public_key(seed)
        return KeyPair(PublicKey(scheme, pub), PrivateKey(scheme, seed))
    if sid in _ECDSA_CURVES:
        curve = _ECDSA_CURVES[sid]
        raw = entropy if entropy is not None else os.urandom(32)
        d = (int.from_bytes(raw, "big") % (curve.n - 1)) + 1
        pub_pt = curve.mul(d, curve.g)
        return KeyPair(
            PublicKey(scheme, sec1_compress(curve, pub_pt)),
            PrivateKey(scheme, d.to_bytes(32, "big")),
        )
    if sid == SPHINCS256_SHA256.scheme_number_id:
        from . import sphincs
        entropy = entropy if entropy is not None else os.urandom(32)
        pub, priv = sphincs.keygen(entropy)
        return KeyPair(PublicKey(scheme, pub), PrivateKey(scheme, priv))
    if sid == RSA_SHA256.scheme_number_id:
        from cryptography.hazmat.primitives.asymmetric import rsa
        from cryptography.hazmat.primitives import serialization
        if entropy is not None:
            raise ValueError("deterministic RSA key generation is not supported")
        key = rsa.generate_private_key(public_exponent=65537, key_size=3072)
        pub = key.public_key().public_bytes(
            serialization.Encoding.DER, serialization.PublicFormat.SubjectPublicKeyInfo)
        priv = key.private_bytes(
            serialization.Encoding.DER, serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption())
        return KeyPair(PublicKey(scheme, pub), PrivateKey(scheme, priv))
    raise ValueError(f"Key generation not supported for scheme {scheme}")
