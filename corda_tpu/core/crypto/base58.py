"""Base58 encoding (Bitcoin alphabet).

Reference parity: core/src/main/java/net/corda/core/crypto/Base58.java — used for
peer queue naming and key display.
"""
from __future__ import annotations

_ALPHABET = "123456789ABCDEFGHJKLMNPQRSTUVWXYZabcdefghijkmnopqrstuvwxyz"
_INDEX = {c: i for i, c in enumerate(_ALPHABET)}


def b58encode(data: bytes) -> str:
    n_zeros = len(data) - len(data.lstrip(b"\x00"))
    num = int.from_bytes(data, "big")
    out = []
    while num > 0:
        num, rem = divmod(num, 58)
        out.append(_ALPHABET[rem])
    return "1" * n_zeros + "".join(reversed(out))


def b58decode(s: str) -> bytes:
    n_ones = len(s) - len(s.lstrip("1"))
    num = 0
    for c in s:
        try:
            num = num * 58 + _INDEX[c]
        except KeyError:
            raise ValueError(f"Invalid base58 character: {c!r}")
    body = num.to_bytes((num.bit_length() + 7) // 8, "big") if num else b""
    return b"\x00" * n_ones + body
