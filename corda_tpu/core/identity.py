"""Identity: well-known and anonymous parties.

Reference parity: core/.../identity/ (Party.kt, AnonymousParty.kt,
AbstractParty.kt) — an ``AbstractParty`` is identified by an owning key (which may
be a CompositeKey for clustered services); a ``Party`` adds a legal X.500-style name.
"""
from __future__ import annotations

from dataclasses import dataclass, field

from .crypto.keys import PublicKey
from .serialization import serializable


@serializable("CordaX500Name")
@dataclass(frozen=True, order=True)
class CordaX500Name:
    """Structured legal name (simplified X.500 DN: O, L, C mandatory — the same
    fields the reference validates in its X500 handling)."""

    organisation: str
    locality: str
    country: str
    common_name: str | None = None
    organisation_unit: str | None = None
    state: str | None = None

    def __post_init__(self):
        if not self.organisation or not self.locality or len(self.country) != 2:
            raise ValueError(
                "CordaX500Name requires organisation, locality and a 2-letter country")

    def __str__(self) -> str:
        parts = [f"O={self.organisation}", f"L={self.locality}", f"C={self.country}"]
        if self.common_name:
            parts.insert(0, f"CN={self.common_name}")
        if self.organisation_unit:
            parts.insert(-2, f"OU={self.organisation_unit}")
        if self.state:
            parts.insert(-1, f"ST={self.state}")
        return ", ".join(parts)

    @staticmethod
    def parse(s: str) -> "CordaX500Name":
        kv = {}
        for part in s.split(","):
            k, _, v = part.strip().partition("=")
            kv[k.strip().upper()] = v.strip()
        return CordaX500Name(
            organisation=kv.get("O", ""), locality=kv.get("L", ""),
            country=kv.get("C", ""), common_name=kv.get("CN"),
            organisation_unit=kv.get("OU"), state=kv.get("ST"))


class AbstractParty:
    """Anything that can own states: identified by its owning key."""

    __slots__ = ("owning_key",)

    def __init__(self, owning_key: PublicKey):
        self.owning_key = owning_key

    # Equality is defined per concrete subclass (strictly same-type) so that
    # AnonymousParty/Party comparisons are symmetric and hash-consistent.
    def __eq__(self, other):
        return type(self) is type(other) and self.owning_key == other.owning_key

    def __hash__(self):
        return hash(self.owning_key)


@serializable("AnonymousParty", to_fields=lambda p: [p.owning_key],
              from_fields=lambda f: AnonymousParty(f[0]))
class AnonymousParty(AbstractParty):
    """A party identified only by key — confidential identities."""

    def __repr__(self):
        return f"AnonymousParty({self.owning_key.to_string_short()[:14]}…)"


@serializable("Party", to_fields=lambda p: [p.name, p.owning_key],
              from_fields=lambda f: Party(f[0], f[1]))
class Party(AbstractParty):
    """A well-known party: legal name + owning key."""

    __slots__ = ("name",)

    def __init__(self, name: CordaX500Name | str, owning_key: PublicKey):
        super().__init__(owning_key)
        if isinstance(name, str):
            name = CordaX500Name.parse(name)
        self.name = name

    def anonymise(self) -> AnonymousParty:
        return AnonymousParty(self.owning_key)

    def ref(self, *reference: int) -> "PartyAndReference":
        from .contracts.structures import PartyAndReference
        return PartyAndReference(self, bytes(reference))

    def __eq__(self, other):
        # Party equality is by key AND name (two services can share a cluster key).
        return (type(other) is Party and self.owning_key == other.owning_key
                and self.name == other.name)

    def __hash__(self):
        return hash((self.owning_key, self.name))

    def __repr__(self):
        return f"Party({self.name})"
