"""WireTransaction — the serialized transaction format whose id is a Merkle root.

Reference parity: WireTransaction.kt:27-120 and MerkleTransaction.kt:16-60:
- ``available_components``: flattened inputs + attachments + outputs + commands,
  then notary (if present), each required signer, the type, the time-window.
- component leaf hash = SHA-256 of the component's canonical serialized bytes
  (``serialized_hash`` — the codec/Merkle coupling).
- ``id`` = root of the Merkle tree over those leaf hashes.

The device-accelerated path computes the same leaf hashes and tree on TPU
(``corda_tpu.ops.sha256.merkle_root``) — bit-exact by construction against
this module.
"""
from __future__ import annotations

from functools import cached_property

from ..contracts.structures import Command, StateRef, TimeWindow, TransactionState
from ..contracts.transaction_types import TransactionType
from ..crypto.keys import PublicKey
from ..crypto.merkle import MerkleTree
from ..crypto.secure_hash import SecureHash
from ..identity import Party
from ..serialization import register_type, serialized_hash, serialize


class TraversableTransaction:
    """Iteration over the flattened components of a (possibly torn) transaction."""

    inputs: tuple[StateRef, ...]
    attachments: tuple[SecureHash, ...]
    outputs: tuple[TransactionState, ...]
    commands: tuple[Command, ...]
    notary: Party | None
    must_sign: tuple[PublicKey, ...]
    type: TransactionType | None
    time_window: TimeWindow | None

    @property
    def available_components(self) -> list:
        out: list = [*self.inputs, *self.attachments, *self.outputs, *self.commands]
        if self.notary is not None:
            out.append(self.notary)
        out.extend(self.must_sign)
        if self.type is not None:
            out.append(self.type)
        if self.time_window is not None:
            out.append(self.time_window)
        return out

    @property
    def available_component_hashes(self) -> list[SecureHash]:
        return [serialized_hash(c) for c in self.available_components]


class WireTransaction(TraversableTransaction):
    """Immutable wire form. All collections are tuples; order is significant and
    consensus-critical (it determines the id)."""

    def __init__(self, inputs=(), attachments=(), outputs=(), commands=(),
                 notary: Party | None = None, must_sign=(),
                 type: TransactionType | None = None,
                 time_window: TimeWindow | None = None):
        self.inputs = tuple(inputs)
        self.attachments = tuple(attachments)
        self.outputs = tuple(outputs)
        self.commands = tuple(commands)
        self.notary = notary
        self.must_sign = tuple(must_sign)
        self.type = type if type is not None else TransactionType.General
        self.time_window = time_window

    @cached_property
    def merkle_tree(self) -> MerkleTree:
        return MerkleTree.get_merkle_tree(self.available_component_hashes)

    @cached_property
    def id(self) -> SecureHash:
        return self.merkle_tree.hash

    @cached_property
    def serialized(self) -> bytes:
        return serialize(self)

    # -- resolution ---------------------------------------------------------
    def to_ledger_transaction(self, services) -> "LedgerTransaction":
        """Resolve StateRefs, attachment hashes and signer identities via the
        ServiceHub into a verifiable LedgerTransaction (WireTransaction.kt:76-108)."""
        from ..contracts.exceptions import (AttachmentResolutionException,
                                            TransactionResolutionException)
        from ..contracts.structures import AuthenticatedObject, StateAndRef
        from .ledger import LedgerTransaction

        resolved_inputs = []
        for ref in self.inputs:
            state = services.load_state(ref)
            if state is None:
                raise TransactionResolutionException(ref.txhash)
            resolved_inputs.append(StateAndRef(state, ref))
        resolved_attachments = []
        for att_id in self.attachments:
            att = services.attachments.open_attachment(att_id)
            if att is None:
                raise AttachmentResolutionException(att_id)
            resolved_attachments.append(att)
        auth_commands = []
        for cmd in self.commands:
            parties = services.identity_service.parties_from_keys(cmd.signers) \
                if hasattr(services, "identity_service") else ()
            auth_commands.append(AuthenticatedObject(
                signers=tuple(cmd.signers), signing_parties=tuple(parties),
                value=cmd.value))
        return LedgerTransaction(
            inputs=tuple(resolved_inputs), outputs=self.outputs,
            commands=tuple(auth_commands), attachments=tuple(resolved_attachments),
            id=self.id, notary=self.notary, must_sign=self.must_sign,
            type=self.type, time_window=self.time_window)

    # -- tear-offs ----------------------------------------------------------
    def build_filtered_transaction(self, predicate) -> "FilteredTransaction":
        from .filtered import FilteredTransaction
        return FilteredTransaction.build_filtered_transaction(self, predicate)

    # -- equality -----------------------------------------------------------
    def __eq__(self, other):
        return isinstance(other, WireTransaction) and self.id == other.id

    def __hash__(self):
        return hash(self.id)

    def __repr__(self):
        return (f"WireTransaction(id={self.id.prefix_chars()}, "
                f"{len(self.inputs)} in, {len(self.outputs)} out, "
                f"{len(self.commands)} cmd)")


register_type(
    "WireTransaction", WireTransaction,
    to_fields=lambda tx: [list(tx.inputs), list(tx.attachments), list(tx.outputs),
                          list(tx.commands), tx.notary, list(tx.must_sign), tx.type,
                          tx.time_window],
    from_fields=lambda f: WireTransaction(*f))
