"""Batch tear-off proof verification on device — the production seam for
``ops.sha256``'s Merkle kernels.

Reference parity: the oracle's bulk attestation path verifies one
FilteredTransaction per request (NodeInterestRates.kt:149-180 →
MerkleTransaction.kt:70-170 → PartialMerkleTree host hashing); at load the
per-proof host SHA-256 walk is the bottleneck (BASELINE.md config 3).  Here
N proofs verify together: every partial tree's internal nodes are grouped
into depth rounds (a node's children always resolve in an earlier round),
and each round's 64-byte (left ‖ right) concatenations hash in ONE device
``hash_pairs`` call — across a thousand tear-offs a round carries thousands
of lanes, exactly the batch shape the VPU wants.  Below
``DEVICE_CROSSOVER`` pairs a round stays on hashlib (device dispatch floor;
same crossover reasoning as verifier/batcher.py).

Bit-exactness: ``hash_pairs`` is differentially tested against hashlib
(tests/test_ops_sha256.py) and this module against
``FilteredTransaction.verify`` (tests/test_batch_merkle.py).
"""
from __future__ import annotations

import hashlib

import numpy as np

from ..crypto.merkle import _IncludedLeaf, _Leaf, _Node
from ..crypto.secure_hash import SecureHash

#: Minimum pairs in a round before it routes to the device kernel.
#: MEASURED on the tunneled v5e (BASELINE r5): hashlib does ~1.15M 64-byte
#: hashes/s on one host core while a device round trip pays the ~140ms
#: tunnel dispatch floor — breakeven is ~10^5 hashes PER ROUND, far above
#: any per-transaction tear-off tree (oracle bulk verification of 2048
#: small proofs ran 30k proofs/s host vs 4.4k via the device).  The host
#: path is therefore the production default; the device path stays
#: bit-exact (tests force it with a tiny crossover) for locally-attached
#: TPU deployments, where the ~ms dispatch floor moves breakeven down to
#: ~10^3 — pass an explicit ``device_crossover`` there.
DEVICE_CROSSOVER = 1 << 17

#: Hard depth cap on a partial tree walk.  A genuine proof over K
#: components is ~log2(K) deep (depth 64 covers 10^19 leaves); anything
#:  deeper is a hostile/corrupt structure built to exhaust the verifier.
#: The traversal is ITERATIVE, so a deep chain can't blow the Python
#: recursion limit — the cap just bounds the work and marks that one
#: member False while the rest of the batch verifies normally.
MAX_PROOF_DEPTH = 512


def _walk_partial_tree(root, values: dict, rounds: list,
                       included: list) -> bool:
    """Iterative post-order walk of one ftx's partial tree into ``values``
    (node id → hash bytes for resolved nodes) and ``rounds`` (internal
    nodes grouped by depth).  Returns False — leaving the caller's dicts
    untouched — on a malformed node type or a tree deeper than
    ``MAX_PROOF_DEPTH``."""
    local_values: dict[int, bytes] = {}
    local_rounds: list[list[_Node]] = []
    local_included: list[bytes] = []
    depth_of: dict[int, int] = {}
    stack: list[tuple] = [(root, False)]
    while stack:
        # a left-leaning chain holds ~its depth in unvisited frames; bail
        # before a hostile 10^6-node path burns CPU on a doomed proof
        if len(stack) > 2 * MAX_PROOF_DEPTH + 2:
            return False
        node, visited = stack.pop()
        if isinstance(node, _IncludedLeaf):
            local_values[id(node)] = node.hash.bytes
            local_included.append(node.hash.bytes)
            depth_of[id(node)] = 0
        elif isinstance(node, _Leaf):
            local_values[id(node)] = node.hash.bytes
            depth_of[id(node)] = 0
        elif isinstance(node, _Node):
            if not visited:
                stack.append((node, True))
                stack.append((node.right, False))
                stack.append((node.left, False))
            else:
                d = max(depth_of[id(node.left)],
                        depth_of[id(node.right)]) + 1
                if d > MAX_PROOF_DEPTH:
                    return False
                while len(local_rounds) < d:
                    local_rounds.append([])
                local_rounds[d - 1].append(node)
                depth_of[id(node)] = d
        else:
            return False   # not a partial-tree node at all
    values.update(local_values)
    while len(rounds) < len(local_rounds):
        rounds.append([])
    for i, rnd in enumerate(local_rounds):
        rounds[i].extend(rnd)
    included.extend(local_included)
    return True


def verify_filtered_batch(ftxs, device_crossover: int = DEVICE_CROSSOVER,
                          use_device: bool = True) -> list[bool]:
    """Verify N FilteredTransactions' Merkle proofs together.

    Returns one bool per ftx: True iff the partial tree rebuilds to
    ``root_hash`` AND the included leaves are exactly the revealed
    components (the same two checks as ``FilteredTransaction.verify``).
    An ftx with no revealed components verifies False (the single-item
    API raises ValueError there), as does one whose partial tree is
    malformed or hostile-deep (``MAX_PROOF_DEPTH``) — a batch must not
    let one malformed member abort the rest (the per-item-isolation rule
    of verifier/batcher.py)."""
    values: dict[int, bytes] = {}
    rounds: list[list[_Node]] = []
    per_ftx: list[tuple] = []

    for ftx in ftxs:
        included: list[bytes] = []
        try:
            root = ftx.partial_merkle_tree.root
            ok = _walk_partial_tree(root, values, rounds, included)
        except Exception:
            root, ok = None, False
        per_ftx.append((root, included) if ok else (None, included))

    for rnd in rounds:
        pairs = b"".join(values[id(n.left)] + values[id(n.right)]
                         for n in rnd)
        if use_device and len(rnd) >= device_crossover:
            from ...ops import sha256 as sha_ops
            arr = np.frombuffer(pairs, dtype=">u4").astype(
                np.uint32).reshape(len(rnd), 16)
            outs = sha_ops.digests_to_bytes(sha_ops.hash_pairs(arr))
        else:
            outs = [hashlib.sha256(pairs[i * 64:(i + 1) * 64]).digest()
                    for i in range(len(rnd))]
        for n, digest in zip(rnd, outs):
            values[id(n)] = digest

    verdicts = []
    for ftx, (root, included) in zip(ftxs, per_ftx):
        if root is None:   # walk rejected it (malformed / too deep)
            verdicts.append(False)
            continue
        try:
            want = {h.bytes for h in
                    ftx.filtered_leaves.available_component_hashes}
            verdicts.append(bool(want)
                            and values[id(root)] == ftx.root_hash.bytes
                            and set(included) == want)
        except Exception:
            verdicts.append(False)
    return verdicts


def batch_roots(leaf_hash_lists: list[list[SecureHash]],
                device_crossover: int = DEVICE_CROSSOVER,
                use_device: bool = True) -> list[SecureHash]:
    """Merkle roots for N transactions' component-hash lists in size-grouped
    device batches (MerkleTree.root_hash semantics: zero-pad each list to
    the next power of two, single-SHA-256 combine).  The bulk sibling of
    ``WireTransaction.id`` for ledger replay / loadtest firehoses."""
    from ..crypto.merkle import MerkleTree, pad_to_power_of_two
    out: list[SecureHash | None] = [None] * len(leaf_hash_lists)
    by_size: dict[int, list[int]] = {}
    for i, hashes in enumerate(leaf_hash_lists):
        if not hashes:
            raise ValueError("Cannot calculate Merkle root on empty hash list.")
        padded = pad_to_power_of_two(hashes)
        by_size.setdefault(len(padded), []).append(i)
    for size, idxs in by_size.items():
        if not use_device or len(idxs) * max(size // 2, 1) < device_crossover:
            for i in idxs:
                out[i] = MerkleTree.root_hash(leaf_hash_lists[i])
            continue
        from ...ops import sha256 as sha_ops
        stacked = np.stack([
            sha_ops.digests_from_bytes(
                [h.bytes for h in pad_to_power_of_two(leaf_hash_lists[i])])
            for i in idxs])                       # (B, size, 8)
        roots = sha_ops.digests_to_bytes(sha_ops.merkle_root(stacked))
        for i, rb in zip(idxs, roots):
            out[i] = SecureHash(rb)
    return out
