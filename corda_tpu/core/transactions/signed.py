"""SignedTransaction — serialized wire bytes + signatures over the id.

Reference parity: SignedTransaction.kt — checkSignaturesAreValid (:96-100) verifies
each signature cryptographically against the id; verifySignatures (:71-85) then
checks the *coverage* of required keys (CompositeKey thresholds included), with an
``allowed_to_be_missing`` escape for counterparties collecting signatures.

The TPU path batches the per-signature EC verifications of MANY transactions into
one device call (the north-star seam); coverage checking stays host-side.
"""
from __future__ import annotations

from functools import cached_property

from ..crypto.keys import PublicKey
from ..crypto.secure_hash import SecureHash
from ..crypto.signatures import DigitalSignatureWithKey, SignatureException
from ..serialization import deserialize, register_type
from .wire import WireTransaction


class SignaturesMissingException(SignatureException):
    def __init__(self, missing: set[PublicKey], descriptions: list[str], id: SecureHash):
        super().__init__(f"Missing signatures for {descriptions} on transaction "
                         f"{id.prefix_chars()}")
        self.missing = missing
        self.id = id


class SignedTransaction:
    def __init__(self, tx_bits: bytes, sigs: tuple[DigitalSignatureWithKey, ...]):
        if not sigs:
            raise ValueError("Tried to instantiate a SignedTransaction without signatures")
        self.tx_bits = bytes(tx_bits)
        self.sigs = tuple(sigs)

    @staticmethod
    def of(wtx: WireTransaction, sigs) -> "SignedTransaction":
        stx = SignedTransaction(wtx.serialized, tuple(sigs))
        stx.__dict__["tx"] = wtx  # prime the cache; avoids a deserialize round-trip
        return stx

    @cached_property
    def tx(self) -> WireTransaction:
        wtx = deserialize(self.tx_bits)
        if not isinstance(wtx, WireTransaction):
            raise ValueError("tx_bits do not contain a WireTransaction")
        return wtx

    @property
    def id(self) -> SecureHash:
        return self.tx.id

    @property
    def inputs(self):
        return self.tx.inputs

    @property
    def notary(self):
        return self.tx.notary

    # -- signature checking -------------------------------------------------
    def check_signatures_are_valid(self) -> None:
        """Cryptographically verify every attached signature against the id.
        Does NOT check coverage (SignedTransaction.kt:96-100)."""
        for sig in self.sigs:
            sig.verify(self.id.bytes)

    def verify_signatures(self, *allowed_to_be_missing: PublicKey) -> set[PublicKey]:
        """Full check: all sigs valid AND every required key fulfilled, except those
        explicitly allowed to be missing. Returns the missing set."""
        self.check_signatures_are_valid()
        missing = self.get_missing_signatures()
        if missing:
            allowed = set(allowed_to_be_missing)
            needed = missing - allowed
            if needed:
                raise SignaturesMissingException(
                    needed, [k.to_string_short() for k in needed], self.id)
        return missing

    def get_missing_signatures(self) -> set[PublicKey]:
        sig_keys = {s.by for s in self.sigs}
        return {k for k in self.tx.must_sign if not k.is_fulfilled_by(sig_keys)}

    # -- combination --------------------------------------------------------
    def plus(self, *sigs: DigitalSignatureWithKey) -> "SignedTransaction":
        combined = self.sigs + tuple(s for s in sigs if s not in self.sigs)
        stx = SignedTransaction(self.tx_bits, combined)
        if "tx" in self.__dict__:
            stx.__dict__["tx"] = self.__dict__["tx"]
        return stx

    def with_additional_signature(self, sig: DigitalSignatureWithKey) -> "SignedTransaction":
        return self.plus(sig)

    # -- resolution / full verify -------------------------------------------
    def to_ledger_transaction(self, services):
        return self.tx.to_ledger_transaction(services)

    def verify(self, services, check_sufficient_signatures: bool = True) -> None:
        """Synchronous host verify (SignedTransaction.kt:174-178): signatures, then
        resolution, then contract/platform rules."""
        if check_sufficient_signatures:
            self.verify_signatures()
        else:
            self.check_signatures_are_valid()
        self.to_ledger_transaction(services).verify()

    def __eq__(self, other):
        return (isinstance(other, SignedTransaction)
                and self.id == other.id and self.sigs == other.sigs)

    def __hash__(self):
        return hash((self.id, self.sigs))

    def __repr__(self):
        return f"SignedTransaction(id={self.id.prefix_chars()}, {len(self.sigs)} sigs)"


register_type("SignedTransaction", SignedTransaction,
              to_fields=lambda s: [s.tx_bits, list(s.sigs)],
              from_fields=lambda f: SignedTransaction(f[0], tuple(f[1])))
