"""LedgerTransaction — the fully-resolved, verifiable transaction form, and the
contract-facing view handed to contract ``verify()`` code.

Reference parity: LedgerTransaction.kt (verify → type.verify, :62) and
TransactionForContract (Structures.kt groupStates — the grouping combinator the
asset contracts are written against).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

from ..contracts.structures import (Attachment, AuthenticatedObject, StateAndRef,
                                    TimeWindow, TransactionState)
from ..contracts.transaction_types import TransactionType
from ..crypto.keys import PublicKey
from ..crypto.secure_hash import SecureHash
from ..identity import Party


@dataclass(frozen=True)
class InOutGroup:
    """States grouped by a key (e.g. (issuer, currency)) across inputs/outputs."""

    inputs: list
    outputs: list
    grouping_key: Any


@dataclass(frozen=True)
class TransactionForContract:
    """What contract code sees: raw states (not TransactionStates), commands with
    resolved signer identities, and the tx metadata."""

    inputs: tuple  # ContractState...
    outputs: tuple  # ContractState...
    attachments: tuple[Attachment, ...]
    commands: tuple[AuthenticatedObject, ...]
    id: SecureHash
    notary: Party | None
    time_window: TimeWindow | None = None
    input_notary: Party | None = None

    def group_states(self, of_type: type, grouping_fn: Callable[[Any], Any]) -> list[InOutGroup]:
        """Group inputs and outputs of ``of_type`` by ``grouping_fn`` — fungible-asset
        contracts verify conservation per group (Structures.kt groupStates)."""
        groups: dict[Any, InOutGroup] = {}

        def bucket(key):
            if key not in groups:
                groups[key] = InOutGroup([], [], key)
            return groups[key]

        for s in self.inputs:
            if isinstance(s, of_type):
                bucket(grouping_fn(s)).inputs.append(s)
        for s in self.outputs:
            if isinstance(s, of_type):
                bucket(grouping_fn(s)).outputs.append(s)
        return list(groups.values())

    def commands_of_type(self, of_type: type) -> list[AuthenticatedObject]:
        return [c for c in self.commands if isinstance(c.value, of_type)]


class LedgerTransaction:
    """Resolved transaction: inputs are StateAndRefs, attachments are open blobs,
    command signers carry resolved identities. ``verify()`` applies the platform
    rules then contract code; the async/TPU-batched variant goes through
    ``TransactionVerifierService`` instead (Services.kt:544-550 seam)."""

    __slots__ = ("inputs", "outputs", "commands", "attachments", "id", "notary",
                 "must_sign", "type", "time_window")

    def __init__(self, inputs: tuple[StateAndRef, ...],
                 outputs: tuple[TransactionState, ...],
                 commands: tuple[AuthenticatedObject, ...],
                 attachments: tuple[Attachment, ...],
                 id: SecureHash, notary: Party | None,
                 must_sign: tuple[PublicKey, ...],
                 type: TransactionType | None,
                 time_window: TimeWindow | None):
        self.inputs = tuple(inputs)
        self.outputs = tuple(outputs)
        self.commands = tuple(commands)
        self.attachments = tuple(attachments)
        self.id = id
        self.notary = notary
        self.must_sign = tuple(must_sign)
        self.type = type if type is not None else TransactionType.General
        self.time_window = time_window

    def verify(self) -> None:
        """Host-side synchronous verification (LedgerTransaction.kt:62)."""
        self.type.verify(self)

    def to_transaction_for_contract(self) -> TransactionForContract:
        return TransactionForContract(
            inputs=tuple(i.state.data for i in self.inputs),
            outputs=tuple(o.data for o in self.outputs),
            attachments=self.attachments,
            commands=self.commands,
            id=self.id,
            notary=self.notary,
            time_window=self.time_window,
            input_notary=self.inputs[0].state.notary if self.inputs else None)

    def out_ref(self, index: int) -> StateAndRef:
        from ..contracts.structures import StateRef
        return StateAndRef(self.outputs[index], StateRef(self.id, index))

    def __eq__(self, other):
        return isinstance(other, LedgerTransaction) and self.id == other.id

    def __hash__(self):
        return hash(self.id)

    def __repr__(self):
        return f"LedgerTransaction(id={self.id.prefix_chars()})"


# Wire registration: the out-of-process verifier protocol ships whole
# LedgerTransactions (VerifierApi.kt:17-59 parity).
from ..serialization import register_type as _register_type  # noqa: E402

_register_type("AuthenticatedObject", AuthenticatedObject,
               to_fields=lambda a: [list(a.signers), list(a.signing_parties), a.value],
               from_fields=lambda f: AuthenticatedObject(tuple(f[0]), tuple(f[1]), f[2]))
_register_type(
    "LedgerTransaction", LedgerTransaction,
    to_fields=lambda tx: [list(tx.inputs), list(tx.outputs), list(tx.commands),
                          list(tx.attachments), tx.id, tx.notary, list(tx.must_sign),
                          tx.type, tx.time_window],
    from_fields=lambda f: LedgerTransaction(*f))
