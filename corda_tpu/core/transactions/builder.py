"""TransactionBuilder — mutable collector producing WireTransactions.

Reference parity: TransactionBuilder.kt:1-207 (+ the type-specific builders in
TransactionTypes.kt): add states/commands/attachments, auto-collect required
signer keys, sign, and freeze to wire form.
"""
from __future__ import annotations

from ..contracts.structures import (Attachment, Command, CommandData, StateAndRef,
                                    StateRef, TimeWindow, TransactionState)
from ..contracts.transaction_types import TransactionType
from ..crypto.keys import KeyPair, PublicKey
from ..crypto.secure_hash import SecureHash
from ..crypto.signatures import Crypto, DigitalSignatureWithKey
from ..identity import Party
from .signed import SignedTransaction
from .wire import WireTransaction


class TransactionBuilder:
    def __init__(self, type: TransactionType | None = None,
                 notary: Party | None = None):
        self.type = type if type is not None else TransactionType.General
        self.notary = notary
        self.inputs: list[StateRef] = []
        self.attachments: list[SecureHash] = []
        self.outputs: list[TransactionState] = []
        self.commands: list[Command] = []
        self.signers: set[PublicKey] = set()
        self.time_window: TimeWindow | None = None
        self._current_sigs: list[DigitalSignatureWithKey] = []

    # -- adding components ---------------------------------------------------
    def with_items(self, *items) -> "TransactionBuilder":
        for item in items:
            if isinstance(item, StateAndRef):
                self.add_input_state(item)
            elif isinstance(item, TransactionState):
                self.add_output_state(item)
            elif isinstance(item, SecureHash):
                self.add_attachment(item)
            elif isinstance(item, Command):
                self.add_command(item)
            elif isinstance(item, TimeWindow):
                self.set_time_window(item)
            else:
                raise ValueError(f"Wrong argument type: {type(item)!r}")
        return self

    def add_input_state(self, state_and_ref: StateAndRef) -> "TransactionBuilder":
        self._check_not_signed()
        notary = state_and_ref.state.notary
        if self.notary is None:
            # Adopt the first input's notary (reference TransactionBuilder behavior)
            # so mismatches surface here, not later at ledger verification.
            self.notary = notary
        elif notary != self.notary:
            raise ValueError(
                f"Input state requires notary {notary} which differs from the "
                f"transaction's notary {self.notary}")
        if self.type == TransactionType.NotaryChange:
            # NotaryChange builders auto-add all participants as signers
            # (TransactionTypes.kt NotaryChange.Builder).
            for p in state_and_ref.state.data.participants:
                self.signers.add(getattr(p, "owning_key", p))
        self.signers.add(notary.owning_key)
        self.inputs.append(state_and_ref.ref)
        return self

    def add_output_state(self, state, notary: Party | None = None,
                         encumbrance: int | None = None) -> "TransactionBuilder":
        self._check_not_signed()
        if isinstance(state, TransactionState):
            self.outputs.append(state)
        else:
            notary = notary or self.notary
            if notary is None:
                raise ValueError("Need a notary to add a raw output state")
            self.outputs.append(TransactionState(state, notary, encumbrance))
        return self

    def add_command(self, command_or_data, *keys: PublicKey) -> "TransactionBuilder":
        self._check_not_signed()
        if isinstance(command_or_data, Command):
            cmd = command_or_data
        else:
            cmd = Command(command_or_data, tuple(keys))
        self.signers.update(cmd.signers)
        self.commands.append(cmd)
        return self

    def add_attachment(self, attachment_id: SecureHash) -> "TransactionBuilder":
        self._check_not_signed()
        self.attachments.append(attachment_id)
        return self

    def set_time_window(self, time_window: TimeWindow) -> "TransactionBuilder":
        self._check_not_signed()
        if self.notary is None:
            raise ValueError("Only notarised transactions can have a time-window")
        self.time_window = time_window
        return self

    # -- signing / freezing --------------------------------------------------
    def to_wire_transaction(self) -> WireTransaction:
        return WireTransaction(
            inputs=tuple(self.inputs), attachments=tuple(self.attachments),
            outputs=tuple(self.outputs), commands=tuple(self.commands),
            notary=self.notary, must_sign=tuple(sorted(self.signers)),
            type=self.type, time_window=self.time_window)

    def sign_with(self, key_pair: KeyPair) -> "TransactionBuilder":
        wtx = self.to_wire_transaction()
        self._current_sigs.append(Crypto.sign_with_key(key_pair, wtx.id.bytes))
        return self

    def to_signed_transaction(self, check_sufficient_signatures: bool = True) -> SignedTransaction:
        if not self._current_sigs:
            raise ValueError("No signatures collected; call sign_with first")
        stx = SignedTransaction.of(self.to_wire_transaction(), tuple(self._current_sigs))
        if check_sufficient_signatures:
            stx.verify_signatures()
        return stx

    def _check_not_signed(self):
        if self._current_sigs:
            raise ValueError(
                "Adding components to a transaction after it's been signed "
                "would invalidate the signatures")
