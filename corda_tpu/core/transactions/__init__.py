"""Transaction types: wire format, signed wrapper, resolved (verifiable) form,
tear-offs and the builder.

Reference parity: core/.../transactions/ (WireTransaction.kt, SignedTransaction.kt,
LedgerTransaction.kt, MerkleTransaction.kt, TransactionBuilder.kt).
"""
from .wire import WireTransaction, TraversableTransaction
from .signed import SignedTransaction, SignaturesMissingException
from .ledger import LedgerTransaction, TransactionForContract, InOutGroup
from .filtered import FilteredLeaves, FilteredTransaction
from .builder import TransactionBuilder

__all__ = [
    "WireTransaction", "TraversableTransaction", "SignedTransaction",
    "SignaturesMissingException", "LedgerTransaction", "TransactionForContract",
    "InOutGroup", "FilteredLeaves", "FilteredTransaction", "TransactionBuilder",
]
