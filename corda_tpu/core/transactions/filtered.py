"""Transaction tear-offs: FilteredLeaves + FilteredTransaction.

Reference parity: MerkleTransaction.kt:70-170 — reveal a predicate-selected subset
of components plus a partial Merkle tree proving membership under the tx id, so
oracles/non-validating notaries sign without seeing the rest (privacy).
"""
from __future__ import annotations

from dataclasses import dataclass

from ..crypto.merkle import MerkleTree, PartialMerkleTree
from ..crypto.secure_hash import SecureHash
from ..serialization import register_type, serialized_hash
from .wire import TraversableTransaction, WireTransaction


class FilteredLeaves(TraversableTransaction):
    """The revealed components of a torn transaction."""

    def __init__(self, inputs=(), attachments=(), outputs=(), commands=(),
                 notary=None, must_sign=(), type=None, time_window=None):
        self.inputs = tuple(inputs)
        self.attachments = tuple(attachments)
        self.outputs = tuple(outputs)
        self.commands = tuple(commands)
        self.notary = notary
        self.must_sign = tuple(must_sign)
        self.type = type
        self.time_window = time_window

    def check_with_fun(self, checking_fun) -> bool:
        """Force type checking over every revealed component so a signer can't be
        tricked into signing over unexpected extras (MerkleTransaction.kt:95-100)."""
        checks = [checking_fun(c) for c in self.available_components]
        return bool(checks) and all(checks)

    def __eq__(self, other):
        return (isinstance(other, FilteredLeaves)
                and self.available_components == other.available_components)

    def __hash__(self):
        return hash(tuple(self.available_component_hashes))


@dataclass(frozen=True)
class FilteredTransaction:
    root_hash: SecureHash
    filtered_leaves: FilteredLeaves
    partial_merkle_tree: PartialMerkleTree

    @staticmethod
    def build_filtered_transaction(wtx: WireTransaction, predicate) -> "FilteredTransaction":
        def keep(items):
            return tuple(i for i in items if predicate(i))

        leaves = FilteredLeaves(
            inputs=keep(wtx.inputs),
            attachments=keep(wtx.attachments),
            outputs=keep(wtx.outputs),
            commands=keep(wtx.commands),
            notary=wtx.notary if wtx.notary is not None and predicate(wtx.notary) else None,
            must_sign=keep(wtx.must_sign),
            type=wtx.type if wtx.type is not None and predicate(wtx.type) else None,
            time_window=(wtx.time_window
                         if wtx.time_window is not None and predicate(wtx.time_window)
                         else None),
        )
        included = leaves.available_component_hashes
        pmt = PartialMerkleTree.build(wtx.merkle_tree, included)
        return FilteredTransaction(wtx.id, leaves, pmt)

    def verify(self) -> bool:
        """Check every revealed component is proven under ``root_hash``."""
        hashes = self.filtered_leaves.available_component_hashes
        if not hashes:
            raise ValueError("Transaction without included leaves cannot be verified")
        return self.partial_merkle_tree.verify(self.root_hash, hashes)


# -- wire registrations ------------------------------------------------------

def _tree_to_wire(node) -> list:
    from ..crypto.merkle import _IncludedLeaf, _Leaf, _Node
    if isinstance(node, _IncludedLeaf):
        return [0, node.hash]
    if isinstance(node, _Leaf):
        return [1, node.hash]
    return [2, _tree_to_wire(node.left), _tree_to_wire(node.right)]


def _tree_from_wire(w):
    from ..crypto.merkle import _IncludedLeaf, _Leaf, _Node
    if w[0] == 0:
        return _IncludedLeaf(w[1])
    if w[0] == 1:
        return _Leaf(w[1])
    return _Node(_tree_from_wire(w[1]), _tree_from_wire(w[2]))


register_type("PartialMerkleTree", PartialMerkleTree,
              to_fields=lambda t: [_tree_to_wire(t.root)],
              from_fields=lambda f: PartialMerkleTree(_tree_from_wire(f[0])))
register_type(
    "FilteredLeaves", FilteredLeaves,
    to_fields=lambda l: [list(l.inputs), list(l.attachments), list(l.outputs),
                         list(l.commands), l.notary, list(l.must_sign), l.type,
                         l.time_window],
    from_fields=lambda f: FilteredLeaves(*f))
register_type("FilteredTransaction", FilteredTransaction,
              to_fields=lambda t: [t.root_hash, t.filtered_leaves, t.partial_merkle_tree],
              from_fields=lambda f: FilteredTransaction(*f))
