"""Core ledger algebra: the layer every other component builds on.

Mirrors the role of the reference's ``core/`` module (SURVEY.md §2.1): depends on
nothing framework-internal; everything depends on it.
"""
