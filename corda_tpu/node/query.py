"""Vault query criteria: the composable query API over vault state.

Reference parity: core/node/services/vault/QueryCriteria.kt:1-131
(VaultQueryCriteria, LinearStateQueryCriteria, FungibleAssetQueryCriteria,
VaultCustomQueryCriteria, And/Or composition), QueryCriteriaUtils.kt:1-297
(ColumnPredicate, PageSpecification, Sort), and the role of
HibernateQueryCriteriaParser (vault/HibernateQueryCriteriaParser.kt:1-437 —
criteria → JPA). Here criteria evaluate directly as predicates over the
in-memory vault index: the SQL engine is a JVM storage concern; the TPU
build's vault is a host-side index whose query cost is negligible next to
the device verification path, so predicate evaluation replaces query
compilation by design.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from datetime import datetime
from typing import Any, Callable

DEFAULT_PAGE_SIZE = 200
MAX_PAGE_SIZE = 10_000


class VaultQueryError(Exception):
    pass


# ---------------------------------------------------------------------------
# Column predicates (QueryCriteriaUtils.kt ColumnPredicate)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class ColumnPredicate:
    """A comparison over one extracted value. ``op`` ∈ {==, !=, >, >=, <, <=,
    between, in, not_in, like, is_null, not_null}."""

    op: str
    value: Any = None
    to_value: Any = None    # upper bound for "between"

    def test(self, v: Any) -> bool:
        if self.op == "is_null":
            return v is None
        if self.op == "not_null":
            return v is not None
        if v is None:
            return False
        if self.op == "==":
            return v == self.value
        if self.op == "!=":
            return v != self.value
        if self.op == ">":
            return v > self.value
        if self.op == ">=":
            return v >= self.value
        if self.op == "<":
            return v < self.value
        if self.op == "<=":
            return v <= self.value
        if self.op == "between":
            return self.value <= v <= self.to_value
        if self.op == "in":
            return v in self.value
        if self.op == "not_in":
            return v not in self.value
        if self.op == "like":  # SQL LIKE with % wildcards, over str(v)
            import fnmatch
            return fnmatch.fnmatch(str(v), str(self.value).replace("%", "*"))
        raise VaultQueryError(f"unknown predicate op {self.op!r}")


def equal(v) -> ColumnPredicate: return ColumnPredicate("==", v)
def not_equal(v) -> ColumnPredicate: return ColumnPredicate("!=", v)
def greater_than(v) -> ColumnPredicate: return ColumnPredicate(">", v)
def greater_than_or_equal(v) -> ColumnPredicate: return ColumnPredicate(">=", v)
def less_than(v) -> ColumnPredicate: return ColumnPredicate("<", v)
def less_than_or_equal(v) -> ColumnPredicate: return ColumnPredicate("<=", v)
def between(lo, hi) -> ColumnPredicate: return ColumnPredicate("between", lo, hi)
def in_collection(vs) -> ColumnPredicate: return ColumnPredicate("in", tuple(vs))
def like(pattern: str) -> ColumnPredicate: return ColumnPredicate("like", pattern)


@dataclass(frozen=True)
class TimeCondition:
    """Filter on when the vault recorded/consumed the state
    (QueryCriteria.TimeCondition; type ∈ {recorded, consumed})."""

    type: str
    predicate: ColumnPredicate


# ---------------------------------------------------------------------------
# Paging and sorting (QueryCriteriaUtils.kt PageSpecification / Sort)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class PageSpecification:
    page_number: int = 1       # 1-based, as in the reference
    page_size: int = DEFAULT_PAGE_SIZE

    def __post_init__(self):
        if self.page_number < 1 or not (0 < self.page_size <= MAX_PAGE_SIZE):
            raise VaultQueryError(
                f"invalid page specification {self.page_number}/{self.page_size}")


@dataclass(frozen=True)
class Sort:
    """Ordered sort columns: (attribute, direction) pairs, direction ∈
    {ASC, DESC}. Attributes: state_ref, recorded_time, consumed_time,
    quantity, or a dotted path into the state data (e.g. "amount.quantity")."""

    columns: tuple = (("state_ref", "ASC"),)

    def __post_init__(self):
        for attr, direction in self.columns:
            if direction not in ("ASC", "DESC"):
                raise VaultQueryError(f"bad sort direction {direction!r}")


@dataclass(frozen=True)
class Page:
    """One page of results plus the total matching count
    (Vault.Page: states + totalStatesAvailable)."""

    states: tuple
    total_states_available: int


# ---------------------------------------------------------------------------
# Vault records (what criteria evaluate against)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class VaultRecord:
    """One vault entry with its query-relevant metadata."""

    sar: Any                       # StateAndRef
    status: str                    # "unconsumed" | "consumed"
    recorded_time: datetime | None = None
    consumed_time: datetime | None = None
    locked_by: str | None = None   # soft-lock holder (flow id)


def _participant_keys(state_data) -> set:
    keys = set()
    for p in getattr(state_data, "participants", []):
        k = getattr(p, "owning_key", p)
        keys.update(getattr(k, "keys", (k,)))
    return keys


def _keys_of(parties_or_keys) -> set:
    out = set()
    for p in parties_or_keys:
        k = getattr(p, "owning_key", p)
        out.update(getattr(k, "keys", (k,)))
    return out


def _attr_path(obj, path: str):
    for part in path.split("."):
        obj = getattr(obj, part, None)
        if obj is None:
            return None
    return obj


# ---------------------------------------------------------------------------
# Criteria (QueryCriteria.kt)
# ---------------------------------------------------------------------------

class QueryCriteria:
    def matches(self, rec: VaultRecord) -> bool:
        raise NotImplementedError

    def __and__(self, other: "QueryCriteria") -> "QueryCriteria":
        return AndComposition(self, other)

    def __or__(self, other: "QueryCriteria") -> "QueryCriteria":
        return OrComposition(self, other)


@dataclass(frozen=True)
class AndComposition(QueryCriteria):
    left: QueryCriteria
    right: QueryCriteria

    def matches(self, rec):
        return self.left.matches(rec) and self.right.matches(rec)


@dataclass(frozen=True)
class OrComposition(QueryCriteria):
    left: QueryCriteria
    right: QueryCriteria

    def matches(self, rec):
        return self.left.matches(rec) or self.right.matches(rec)


def _status_ok(rec_status: str, wanted: str) -> bool:
    return wanted == "all" or rec_status == wanted


class _CommonCriteria(QueryCriteria):
    """Shared axes: status, participants (QueryCriteria.CommonQueryCriteria)."""

    def _common_ok(self, rec: VaultRecord) -> bool:
        if not _status_ok(rec.status, self.status):
            return False
        if self.participants is not None:
            wanted = _keys_of(self.participants)
            if wanted.isdisjoint(_participant_keys(rec.sar.state.data)):
                return False
        return True


@dataclass(frozen=True)
class VaultQueryCriteria(_CommonCriteria):
    """The general axes (QueryCriteria.VaultQueryCriteria): status, state
    types, state refs, notary, soft-locking, time conditions, participants."""

    status: str = "unconsumed"
    contract_state_types: tuple | None = None
    state_refs: tuple | None = None
    notary: tuple | None = None
    soft_locking: str | None = None       # "locked_only" | "unlocked_only"
    time_condition: TimeCondition | None = None
    participants: tuple | None = None

    def matches(self, rec):
        if not self._common_ok(rec):
            return False
        if (self.contract_state_types is not None
                and not isinstance(rec.sar.state.data,
                                   tuple(self.contract_state_types))):
            return False
        if self.state_refs is not None and rec.sar.ref not in self.state_refs:
            return False
        if self.notary is not None and rec.sar.state.notary not in self.notary:
            return False
        if self.soft_locking == "locked_only" and rec.locked_by is None:
            return False
        if self.soft_locking == "unlocked_only" and rec.locked_by is not None:
            return False
        if self.time_condition is not None:
            t = (rec.recorded_time if self.time_condition.type == "recorded"
                 else rec.consumed_time)
            if not self.time_condition.predicate.test(t):
                return False
        return True


@dataclass(frozen=True)
class LinearStateQueryCriteria(_CommonCriteria):
    """LinearState axes: linear ids / external ids
    (QueryCriteria.LinearStateQueryCriteria)."""

    uuids: tuple | None = None
    external_ids: tuple | None = None
    status: str = "unconsumed"
    participants: tuple | None = None

    def matches(self, rec):
        if not self._common_ok(rec):
            return False
        lid = getattr(rec.sar.state.data, "linear_id", None)
        if lid is None:
            return False
        if self.uuids is not None and lid.id not in self.uuids:
            return False
        if (self.external_ids is not None
                and lid.external_id not in self.external_ids):
            return False
        return True


@dataclass(frozen=True)
class FungibleAssetQueryCriteria(_CommonCriteria):
    """FungibleAsset axes: owner, quantity, issuer party/reference
    (QueryCriteria.FungibleAssetQueryCriteria)."""

    owner: tuple | None = None
    quantity: ColumnPredicate | None = None
    issuer: tuple | None = None
    issuer_ref: tuple | None = None
    status: str = "unconsumed"
    participants: tuple | None = None

    def matches(self, rec):
        if not self._common_ok(rec):
            return False
        data = rec.sar.state.data
        amount = getattr(data, "amount", None)
        if amount is None:
            return False
        if self.owner is not None:
            owner_key = getattr(data, "owner", None)
            k = getattr(owner_key, "owning_key", owner_key)
            leaves = set(getattr(k, "keys", (k,)))
            if leaves.isdisjoint(_keys_of(self.owner)):
                return False
        if self.quantity is not None and not self.quantity.test(amount.quantity):
            return False
        issued = getattr(amount, "token", None)
        issuer_pr = getattr(issued, "issuer", None)
        if self.issuer is not None:
            if issuer_pr is None or issuer_pr.party not in self.issuer:
                return False
        if self.issuer_ref is not None:
            if issuer_pr is None or issuer_pr.reference not in self.issuer_ref:
                return False
        return True


@dataclass(frozen=True)
class CustomQueryCriteria(_CommonCriteria):
    """Attribute-expression axis (QueryCriteria.VaultCustomQueryCriteria):
    a dotted attribute path into the state data + a column predicate."""

    attribute: str = ""
    predicate: ColumnPredicate = field(default_factory=lambda: ColumnPredicate("not_null"))
    status: str = "unconsumed"
    participants: tuple | None = None

    def matches(self, rec):
        if not self._common_ok(rec):
            return False
        return self.predicate.test(_attr_path(rec.sar.state.data, self.attribute))


# ---------------------------------------------------------------------------
# Execution (sorting + paging over filtered records)
# ---------------------------------------------------------------------------

_SORT_EXTRACTORS: dict[str, Callable[[VaultRecord], Any]] = {
    "state_ref": lambda r: (r.sar.ref.txhash.bytes, r.sar.ref.index),
    "recorded_time": lambda r: r.recorded_time,
    "consumed_time": lambda r: r.consumed_time,
    "quantity": lambda r: getattr(getattr(r.sar.state.data, "amount", None),
                                  "quantity", None),
}


def _sort_key(rec: VaultRecord, attr: str):
    ex = _SORT_EXTRACTORS.get(attr)
    v = ex(rec) if ex is not None else _attr_path(rec.sar.state.data, attr)
    # None sorts first, deterministically; wrap to keep mixed types orderable
    return (v is not None, v)


def run_query(records, criteria: QueryCriteria | None,
              paging: PageSpecification | None, sorting: Sort | None) -> Page:
    """Filter → sort → page. Mirrors the reference's guard: result sets larger
    than DEFAULT_PAGE_SIZE require an explicit PageSpecification."""
    if criteria is None:
        criteria = VaultQueryCriteria()
    hits = [r for r in records if criteria.matches(r)]
    sorting = sorting or Sort()
    for attr, direction in reversed(sorting.columns):   # stable multi-key
        hits.sort(key=lambda r: _sort_key(r, attr), reverse=direction == "DESC")
    total = len(hits)
    if paging is None:
        if total > DEFAULT_PAGE_SIZE:
            raise VaultQueryError(
                f"{total} results: specify a PageSpecification when the "
                f"result set may exceed {DEFAULT_PAGE_SIZE}")
        return Page(tuple(r.sar for r in hits), total)
    lo = (paging.page_number - 1) * paging.page_size
    return Page(tuple(r.sar for r in hits[lo:lo + paging.page_size]), total)
