"""Checkpoint model + storage for the replay-based flow state machine.

Reference parity: CheckpointStorage (node/services/api/CheckpointStorage.kt:10-28)
and DBCheckpointStorage (persistence/DBCheckpointStorage.kt:18-25). A checkpoint
here is NOT a serialized continuation (no Quasar): it is the *replay record* —
flow class + flow fields + the ordered responses consumed at each yield + the
session table. Resume = re-execute `call()` feeding the log (corda_tpu.flows
module docstring).

`FileCheckpointStorage` adds crash-durable atomic persistence (one file per
checkpoint, write-tmp-then-rename — the node_checkpoints table analog).
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field

from ..core.serialization import deserialize, serialize


@dataclass
class SessionSnapshot:
    """Persisted session state (statemachine session table row)."""

    peer_name: str
    our_session_id: int
    peer_session_id: int | None
    state: str
    received: list
    pending_out: list
    group: int = 0  # session group (sub-flow keying, statemachine)


@dataclass
class Checkpoint:
    run_id: str
    flow_class: str           # importable "module.QualName"
    flow_fields: dict         # flow __dict__ minus injected attrs
    response_log: list        # ordered responses consumed at yields
    sessions: list = field(default_factory=list)  # SessionSnapshot list

    @property
    def id(self) -> str:
        return self.run_id


class CheckpointStorage:
    """In-memory checkpoint store (reference CheckpointStorage SPI)."""

    def __init__(self):
        self._checkpoints: dict[str, Checkpoint] = {}

    def add_checkpoint(self, cp: Checkpoint) -> None:
        self._checkpoints[cp.id] = cp

    def remove_checkpoint(self, cp_or_id) -> None:
        cp_id = cp_or_id if isinstance(cp_or_id, str) else cp_or_id.id
        self._checkpoints.pop(cp_id, None)

    def get_all_checkpoints(self) -> list[Checkpoint]:
        return list(self._checkpoints.values())


class FileCheckpointStorage(CheckpointStorage):
    """Durable variant: canonical-codec blobs, atomic replace per checkpoint."""

    def __init__(self, directory: str):
        super().__init__()
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        for name in os.listdir(directory):
            if name.endswith(".ckpt"):
                with open(os.path.join(directory, name), "rb") as f:
                    cp = _checkpoint_from_bytes(f.read())
                self._checkpoints[cp.id] = cp

    def _path(self, cp_id: str) -> str:
        return os.path.join(self.directory, f"{cp_id}.ckpt")

    def add_checkpoint(self, cp: Checkpoint) -> None:
        super().add_checkpoint(cp)
        tmp = self._path(cp.id) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(_checkpoint_to_bytes(cp))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._path(cp.id))

    def remove_checkpoint(self, cp_or_id) -> None:
        cp_id = cp_or_id if isinstance(cp_or_id, str) else cp_or_id.id
        super().remove_checkpoint(cp_id)
        try:
            os.remove(self._path(cp_id))
        except FileNotFoundError:
            pass


class KvCheckpointStorage(CheckpointStorage):
    """Checkpoints on the native kvlog engine (corda_tpu.storage): synced
    crc-framed appends with torn-tail recovery — the DBCheckpointStorage
    durability class without an embedded SQL database."""

    def __init__(self, path: str, use_native: bool | None = None):
        super().__init__()
        from ..storage import KvStore
        self._kv = KvStore(path, use_native=use_native)
        for key, blob in self._kv.items():
            cp = _checkpoint_from_bytes(blob)
            self._checkpoints[cp.id] = cp

    def add_checkpoint(self, cp: Checkpoint) -> None:
        super().add_checkpoint(cp)
        self._kv[cp.id.encode()] = _checkpoint_to_bytes(cp)

    def remove_checkpoint(self, cp_or_id) -> None:
        cp_id = cp_or_id if isinstance(cp_or_id, str) else cp_or_id.id
        super().remove_checkpoint(cp_id)
        key = cp_id.encode()
        if key in self._kv:
            del self._kv[key]

    def close(self) -> None:
        self._kv.close()


def _checkpoint_to_bytes(cp: Checkpoint) -> bytes:
    return serialize([
        cp.run_id, cp.flow_class, cp.flow_fields, cp.response_log,
        [[s.peer_name, s.our_session_id, s.peer_session_id, s.state,
          s.received, s.pending_out, s.group] for s in cp.sessions]])


def _checkpoint_from_bytes(data: bytes) -> Checkpoint:
    run_id, flow_class, fields, log, sessions = deserialize(data)
    return Checkpoint(run_id, flow_class, fields, log,
                      [SessionSnapshot(*s) for s in sessions])
