"""Node — the production runtime assembling every service over TCP.

Reference parity: AbstractNode.start (node/internal/AbstractNode.kt:160-222 —
services assembled in dependency order), Node's messaging/RPC wiring
(internal/Node.kt:83), NodeStartup CLI entry (internal/NodeStartup.kt), the
typed configuration layer (config/NodeConfiguration.kt:34-94 incl.
`verifierType`), and the RPC server request/response protocol (RPCServer.kt +
RPCApi.kt — here framed over the TCP plane with a reply address carried in
the request, observables served as polled snapshots).
"""
from __future__ import annotations

import json
import logging
import os
import uuid
from dataclasses import dataclass, field

from ..core.crypto.keys import KeyPair, PrivateKey, PublicKey, generate_keypair
from ..core.identity import Party
from ..core.serialization import deserialize, register_type, serialize
from ..flows.library import install_core_flows
from ..network.messaging import TopicSession
from ..network.netmap import NetworkMapClient, NetworkMapService
from ..network.tcp import TcpMessagingService
from ..utils.affinity import SerialExecutor
from .checkpoints import FileCheckpointStorage  # noqa: F401 (public re-export)
from .notary import (FileUniquenessProvider, SimpleNotaryService,
                     ValidatingNotaryService)
from .rpc import CordaRPCOps
from .services import NodeInfo, ServiceHub, ServiceInfo
from .statemachine import StateMachineManager

log = logging.getLogger(__name__)

TOPIC_RPC = "rpc.requests"


@dataclass
class NodeConfiguration:
    """Typed config (NodeConfiguration.kt parity). Loadable from JSON —
    the HOCON layering analog is defaults-in-dataclass + file overrides."""

    my_legal_name: str
    host: str = "127.0.0.1"
    port: int = 0                      # 0 = ephemeral
    base_directory: str = "."
    network_map_name: str | None = None
    network_map_address: str | None = None   # "host:port"
    notary: str | None = None          # None | "simple" | "validating"
    verifier_type: str = "InMemory"    # InMemory | Tpu | OutOfProcess
    # with verifier_type=Tpu: shard every device batch over the first N
    # local chips as one SPMD program (jax.sharding.Mesh over ICI) — the
    # config-driven scale-out seam (the reference scales out by launching
    # N verifier JVMs, Verifier.kt:42-79; a TPU host scales ACROSS ITS
    # SLICE instead). None = single chip.
    mesh_devices: int | None = None
    # with verifier_type=OutOfProcess: how many fleet workers the operator
    # runs against this node's queue. The node works with any number
    # attached (competing consumers); /readyz reports fewer-than-expected
    # as a degraded fleet. None = no expectation.
    verifier_workers: int | None = None
    key_seed_hex: str | None = None    # deterministic identity (tests)
    tls: bool = False                  # mutual TLS on the TCP plane
    # shared dev-CA directory (all nodes of one network must agree);
    # default: a "dev-ca" sibling of base_directory
    tls_ca_directory: str | None = None
    # modules imported at boot so their @startable_by_rpc / @initiated_by
    # registrations load — the cordapp classpath scan (AbstractNode.kt:201-206)
    cordapps: list = field(default_factory=lambda: ["corda_tpu.finance"])

    def __post_init__(self):
        # fail at CONSTRUCTION, before a misconfigured node binds sockets,
        # writes its identity or spawns threads: an OutOfProcess/InMemory
        # node silently ignoring mesh_devices would boot without the chips
        # the operator configured (workers take --mesh-devices instead)
        if self.mesh_devices is not None and self.verifier_type != "Tpu":
            raise ValueError(
                "mesh_devices requires verifier_type=Tpu "
                f"(got {self.verifier_type!r}; for OutOfProcess, "
                "pass --mesh-devices to the verifier worker)")
        if (self.verifier_workers is not None
                and self.verifier_type != "OutOfProcess"):
            raise ValueError(
                "verifier_workers requires verifier_type=OutOfProcess "
                f"(got {self.verifier_type!r}) — only the out-of-process "
                "queue has a worker fleet to expect")

    @staticmethod
    def load(path: str) -> "NodeConfiguration":
        with open(path) as f:
            return NodeConfiguration(**json.load(f))

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.__dict__, f, indent=2)


@dataclass(frozen=True)
class RpcRequest:
    request_id: str
    method: str
    args: list
    reply_to: str              # "host:port" of the caller


@dataclass(frozen=True)
class RpcResponse:
    request_id: str
    result: object = None
    error: str | None = None


@dataclass(frozen=True)
class FeedHandle:
    """A server-assigned observable id + the snapshot (the reference
    serializes Observables as ids on the RPC wire, RPCApi.kt:27-60)."""

    feed_id: str
    snapshot: object


@dataclass(frozen=True)
class Observation:
    """One pushed observation for a subscribed feed (RPCApi Observation)."""

    feed_id: str
    payload: object


register_type("rpc.RpcRequest", RpcRequest,
              to_fields=lambda r: [r.request_id, r.method, list(r.args), r.reply_to],
              from_fields=lambda f: RpcRequest(f[0], f[1], list(f[2]), f[3]))
register_type("rpc.RpcResponse", RpcResponse)
register_type("rpc.FeedHandle", FeedHandle)
register_type("rpc.Observation", Observation)


class Node:
    def __init__(self, config: NodeConfiguration):
        self.config = config
        # cordapps must load BEFORE the durable stores open: a restarted
        # node deserializes its recorded transactions at construction, and
        # their state/command types live in the cordapp modules
        import importlib
        for module in config.cordapps:
            importlib.import_module(module)
        os.makedirs(config.base_directory, exist_ok=True)
        self.key_pair = self._load_or_create_identity()
        self.party = Party(config.my_legal_name, self.key_pair.public)
        self.executor = SerialExecutor(f"node-thread({config.my_legal_name})")
        tls_config = None
        if config.tls:
            from ..network.tls import TlsConfig
            ca_dir = config.tls_ca_directory or os.path.join(
                os.path.dirname(os.path.abspath(config.base_directory)), "dev-ca")
            tls_config = TlsConfig.dev(config.base_directory,
                                       str(self.party.name), ca_dir)
        self.messaging = TcpMessagingService(
            str(self.party.name), config.host, config.port,
            self._resolve_address, executor=self.executor, tls=tls_config)

        services = ()
        if config.notary == "simple":
            services = (ServiceInfo(SimpleNotaryService.type_id),)
        elif config.notary == "validating":
            services = (ServiceInfo(ValidatingNotaryService.type_id),)
        self.info = NodeInfo(address=f"{config.host}:{self.messaging.port}",
                             legal_identity=self.party,
                             advertised_services=services)
        self.services = ServiceHub(self.info, self.messaging,
                                   key_pairs=[self.key_pair])
        # fresh (confidential-identity) keys must survive restarts, or the
        # vault replay below would drop states they own as irrelevant
        from .services import KeyManagementService
        self.services.key_management = KeyManagementService(
            [self.key_pair],
            store_path=os.path.join(config.base_directory, "fresh-keys.jsonl"))
        # durable storage on the kvlog engine (native C++ when built, the
        # format-identical Python engine otherwise) — transactions AND
        # checkpoints persist together, or resumed flows would reference
        # transactions a restart forgot
        from .checkpoints import KvCheckpointStorage
        from .services import DurableTransactionStorage
        self.services.storage = DurableTransactionStorage(
            os.path.join(config.base_directory, "transactions.kv"))
        # RESTART path: the vault (and its observers — schema tables,
        # scheduler) is an in-memory index over the durable store; replay
        # the recorded transactions in order so a restarted node still
        # holds its pre-crash states (spends re-consume as they replay)
        stored = self.services.storage.transactions
        if stored:
            self.services.vault.notify_all(stored)
        checkpoint_storage = KvCheckpointStorage(
            os.path.join(config.base_directory, "checkpoints.kv"))
        self.services.verifier_service = self._make_verifier()
        self.smm = StateMachineManager(self.services, checkpoint_storage)
        self.services.smm = self.smm
        # async verify completions (the Verify suspension point) re-enter
        # flows on the node thread, serialized with message handling
        self.smm.scheduler_poke = \
            lambda: self.executor.execute(self.smm.drain_external)
        # flow timers (Sleep / receive timeouts) fire back onto the node
        # thread the same way
        self.smm.timer_driver = self._schedule_flow_timer
        install_core_flows(self.smm)
        self.notary_service = self._make_notary()
        self.rpc_ops = CordaRPCOps(self.services, self.smm)
        self._rpc_flows: dict[str, object] = {}
        # observable streaming (RPCServer.kt + RPCApi.kt:27-60): feed_id →
        # (client address, alive flag); per-client index for disconnect
        # cleanup — a client whose address stops accepting frames has every
        # feed dropped (the artemis binding-removal cleanup analog)
        self._feeds: dict[str, tuple[str, dict]] = {}
        self._client_feeds: dict[str, set] = {}
        self.messaging.on_send_failure = self._on_client_unreachable
        self.network_map_service = None
        self.network_map_client = None

    def _schedule_flow_timer(self, delay_s: float, fire) -> None:
        import threading
        t = threading.Timer(delay_s, lambda: self.executor.execute(fire))
        t.daemon = True
        t.start()

    # -- assembly ------------------------------------------------------------
    def _load_or_create_identity(self) -> KeyPair:
        if self.config.key_seed_hex:
            return generate_keypair(entropy=bytes.fromhex(self.config.key_seed_hex))
        key_file = os.path.join(self.config.base_directory, "identity.key")
        if os.path.exists(key_file):
            with open(key_file, "rb") as f:
                seed = f.read()
        else:
            seed = os.urandom(32)
            with open(key_file, "wb") as f:
                f.write(seed)
        return generate_keypair(entropy=seed)

    def _make_verifier(self):
        # mesh_devices/verifier_type consistency is enforced at
        # NodeConfiguration construction (__post_init__)
        from ..verifier.service import make_verifier_service
        metrics = self.services.monitoring
        if self.config.verifier_type == "OutOfProcess":
            from ..verifier.out_of_process import (
                OutOfProcessTransactionVerifierService)
            return OutOfProcessTransactionVerifierService(
                self.messaging, metrics=metrics,
                expected_workers=self.config.verifier_workers)
        kwargs = {"metrics": metrics}
        if self.config.mesh_devices is not None:
            from ..parallel import make_mesh
            kwargs["mesh"] = make_mesh(self.config.mesh_devices)
        return make_verifier_service(self.config.verifier_type, **kwargs)

    def _make_notary(self):
        if self.config.notary is None:
            return None
        cls = (SimpleNotaryService if self.config.notary == "simple"
               else ValidatingNotaryService)
        commit_log = FileUniquenessProvider(
            os.path.join(self.config.base_directory, "commit.log"))
        svc = cls(self.services, uniqueness=commit_log)
        svc.install(self.smm)
        return svc

    def _resolve_address(self, recipient: str):
        """Directory lookup; bare "host:port" strings resolve literally
        (RPC reply addresses)."""
        info = self.services.network_map_cache.get_node_by_legal_name(recipient)
        if info is not None:
            host, _, port = info.address.rpartition(":")
            return host, int(port)
        if ":" in recipient:
            host, _, port = recipient.rpartition(":")
            try:
                return host, int(port)
            except ValueError:
                return None
        return None

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "Node":
        self.messaging.add_message_handler(TopicSession(TOPIC_RPC),
                                           self._on_rpc)
        if self.config.network_map_name is None:
            # we ARE the network map node: serve the directory and publish our
            # own signed registration so peers learn our real identity key
            import time
            from ..network.netmap import ADD, make_registration
            self.network_map_service = NetworkMapService(
                self.messaging, local_cache=self.services.network_map_cache)
            self.network_map_service.apply_registration(make_registration(
                self.services, self.info, int(time.time() * 1000), ADD))
        else:
            if self.config.network_map_address is None:
                raise ValueError(
                    "network_map_name is set but network_map_address is not")
            # seed the directory with the map node so sends resolve pre-fetch
            map_host, _, map_port = self.config.network_map_address.rpartition(":")
            placeholder = NodeInfo(
                address=f"{map_host}:{map_port}",
                legal_identity=Party(self.config.network_map_name,
                                     _PLACEHOLDER_KEY))
            self.services.network_map_cache.add_node(placeholder)
            self.network_map_client = NetworkMapClient(
                self.services, str(placeholder.legal_identity.name))
            self.network_map_client.subscribe()
            self.network_map_client.register()
            self.network_map_client.fetch()
        self.smm.start()
        log.info("node %s started on %s:%s", self.party.name,
                 self.config.host, self.messaging.port)
        return self

    def stop(self) -> None:
        self.smm.stop()
        self.messaging.stop()
        self.executor.shutdown()
        for store in (self.smm.checkpoints, self.services.storage):
            close = getattr(store, "close", None)
            if close is not None:
                close()

    # -- RPC server ----------------------------------------------------------
    def _on_rpc(self, msg) -> None:
        try:
            req: RpcRequest = deserialize(msg.data)
        except Exception:
            log.exception("malformed RPC request dropped")
            return
        try:
            resp_bytes = serialize(
                RpcResponse(req.request_id, self._dispatch_rpc(req), None))
        except Exception as e:
            # serialization of the RESULT may fail too — the client must still
            # get a typed error instead of a silent timeout
            resp_bytes = serialize(
                RpcResponse(req.request_id, None, f"{type(e).__name__}: {e}"))
        self.messaging.send(TopicSession(TOPIC_RPC, 1), resp_bytes,
                            req.reply_to)

    # -- observable streaming ------------------------------------------------
    def _register_feed(self, feed, client_addr: str) -> FeedHandle:
        """Turn a DataFeed into a server-held subscription that pushes each
        observation to the client's address; the wire sees only the id +
        snapshot (the reference's observable-as-id serialization)."""
        feed_id = uuid.uuid4().hex
        alive = {"on": True}
        self._feeds[feed_id] = (client_addr, alive)
        self._client_feeds.setdefault(client_addr, set()).add(feed_id)

        def push(update):
            if not alive["on"]:
                return
            try:
                payload = serialize(Observation(feed_id, update))
            except Exception as e:
                try:
                    payload = serialize(Observation(
                        feed_id, ("error", f"unserializable update: {e}")))
                except Exception:
                    return
            self.messaging.send(TopicSession(TOPIC_RPC, 2), payload,
                                client_addr)

        feed.subscribe(push)
        return FeedHandle(feed_id, feed.snapshot)

    def _unsubscribe_feed(self, feed_id: str) -> None:
        entry = self._feeds.pop(feed_id, None)
        if entry is not None:
            client_addr, alive = entry
            alive["on"] = False
            self._client_feeds.get(client_addr, set()).discard(feed_id)

    def _on_client_unreachable(self, recipient: str) -> None:
        """Transport gave up on this address: drop all its feeds so dead
        clients do not leak subscriptions (disconnect cleanup), and error
        any flow session awaiting that peer (a parked flow must not wait
        forever on a dead counterparty)."""
        for feed_id in list(self._client_feeds.get(recipient, ())):
            self._unsubscribe_feed(feed_id)
        self._client_feeds.pop(recipient, None)
        self.smm.on_peer_unreachable(recipient)

    def _dispatch_rpc(self, req: RpcRequest):
        if req.method == "unsubscribe_feed":
            self._unsubscribe_feed(req.args[0])
            return None
        if req.method == "start_flow_tracked":
            flow_name, args = req.args[0], req.args[1:]
            fsm, feed = self.rpc_ops.start_tracked_flow_dynamic(
                flow_name, *args)
            self._rpc_flows[fsm.run_id] = fsm
            return self._register_feed(feed, req.reply_to)
        if req.method == "start_flow":
            flow_name, args = req.args[0], req.args[1:]
            fsm = self.rpc_ops.start_flow_dynamic(flow_name, *args)
            self._rpc_flows[fsm.run_id] = fsm
            return fsm.run_id
        if req.method == "flow_result":
            fsm = self._rpc_flows.get(req.args[0])
            if fsm is None:
                raise KeyError(f"unknown flow {req.args[0]}")
            if not fsm.result_future.done():
                return ["running", None]
            try:
                return ["done", fsm.result_future.result()]
            except Exception as e:
                return ["failed", f"{type(e).__name__}: {e}"]
        method = getattr(self.rpc_ops, req.method, None)
        if method is None or req.method.startswith("_"):
            raise AttributeError(f"no such RPC op: {req.method}")
        result = method(*req.args)
        from .rpc import DataFeed
        if isinstance(result, DataFeed):
            # feeds cross the wire as id + snapshot; observations are pushed
            return self._register_feed(result, req.reply_to)
        return result


_PLACEHOLDER_KEY = generate_keypair(entropy=b"\x00" * 32).public
