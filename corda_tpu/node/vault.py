"""Vault: tracks relevant states, streams updates, soft-locks in-flight spends.

Reference parity: NodeVaultService (node/services/vault/NodeVaultService.kt:62
— notifyAll :230, soft locks :261-296), Vault.Update model, the
unconsumed/consumed StateStatus axis of the vault query API
(core/node/services/vault/QueryCriteria.kt), and soft-lock auto-release on
flow completion (VaultSoftLockManager.kt).

The SQL/Hibernate query engine of the reference maps here to predicate-based
in-memory querying (the JDBC layer is a storage backend concern, not an API
one). Two query surfaces: `query()` covers the quick axes used by the finance
layer (status, state type, owners, notary); `query_by()` is the full
QueryCriteria engine (node.query) with linear/fungible/custom criteria,
And/Or composition, time conditions, soft-lock filters, paging and sorting.
"""
from __future__ import annotations

import datetime as _dt
import threading
from dataclasses import dataclass, field

from ..core.contracts.structures import StateAndRef, StateRef
from .query import (Page, PageSpecification, QueryCriteria, Sort, VaultRecord,
                    run_query)


@dataclass(frozen=True)
class VaultUpdate:
    """One atomic vault transition (Vault.Update)."""

    consumed: tuple[StateAndRef, ...]
    produced: tuple[StateAndRef, ...]

    @property
    def is_empty(self) -> bool:
        return not self.consumed and not self.produced


from ..core.serialization import register_type as _register_type  # noqa: E402

# vault updates cross the RPC wire as pushed feed observations
_register_type("vault.VaultUpdate", VaultUpdate,
               to_fields=lambda u: [list(u.consumed), list(u.produced)],
               from_fields=lambda f: VaultUpdate(tuple(f[0]), tuple(f[1])))


class SoftLockError(Exception):
    pass


class NodeVaultService:
    def __init__(self, hub, clock=None):
        self.hub = hub
        self.clock = clock or (lambda: _dt.datetime.now(_dt.timezone.utc))
        self._lock = threading.Lock()
        self._unconsumed: dict[StateRef, StateAndRef] = {}
        self._consumed: dict[StateRef, StateAndRef] = {}
        self._recorded_time: dict[StateRef, _dt.datetime] = {}
        self._consumed_time: dict[StateRef, _dt.datetime] = {}
        self._soft_locks: dict[StateRef, str] = {}      # ref -> lock id (flow id)
        self._observers: list = []
        self._tx_notes: dict = {}                       # tx_id -> [notes]

    # -- transaction notes (CordaRPCOps.addVaultTransactionNote) ------------
    def add_transaction_note(self, tx_id, note: str) -> None:
        with self._lock:
            self._tx_notes.setdefault(tx_id, []).append(note)

    def get_transaction_notes(self, tx_id) -> list[str]:
        with self._lock:
            return list(self._tx_notes.get(tx_id, ()))

    # -- relevance ----------------------------------------------------------
    def _is_relevant(self, state) -> bool:
        our_keys = self.hub.key_management.keys
        participants = getattr(state.data, "participants", [])
        keys = {getattr(p, "owning_key", p) for p in participants}
        return any(any(leaf in our_keys for leaf in k.keys) for k in keys)

    # -- ingestion (NodeVaultService.notifyAll :230) -------------------------
    def notify_all(self, txs) -> list[VaultUpdate]:
        updates = []
        for stx in txs:
            wtx = stx.tx if hasattr(stx, "tx") else stx
            with self._lock:
                now = self.clock()
                consumed = []
                for ref in wtx.inputs:
                    sar = self._unconsumed.pop(ref, None)
                    if sar is not None:
                        self._consumed[ref] = sar
                        self._consumed_time[ref] = now
                        self._soft_locks.pop(ref, None)
                        consumed.append(sar)
                produced = []
                for i, out in enumerate(wtx.outputs):
                    if self._is_relevant(out):
                        sar = StateAndRef(out, StateRef(wtx.id, i))
                        self._unconsumed[sar.ref] = sar
                        self._recorded_time[sar.ref] = now
                        produced.append(sar)
            update = VaultUpdate(tuple(consumed), tuple(produced))
            if not update.is_empty:
                updates.append(update)
                for cb in list(self._observers):
                    cb(update)
        return updates

    def add_update_observer(self, cb) -> None:
        self._observers.append(cb)

    # -- queries -------------------------------------------------------------
    def unconsumed_states(self, state_type: type | None = None,
                          include_soft_locked: bool = True) -> list[StateAndRef]:
        with self._lock:
            out = []
            for sar in self._unconsumed.values():
                if state_type is not None and not isinstance(sar.state.data, state_type):
                    continue
                if not include_soft_locked and sar.ref in self._soft_locks:
                    continue
                out.append(sar)
            return out

    def query(self, state_type: type | None = None, status: str = "unconsumed",
              owner_keys=None, notary=None) -> list[StateAndRef]:
        """The QueryCriteria axes: status ∈ {unconsumed, consumed, all}."""
        with self._lock:
            pools = {"unconsumed": [self._unconsumed], "consumed": [self._consumed],
                     "all": [self._unconsumed, self._consumed]}[status]
            out = []
            for pool in pools:
                for sar in pool.values():
                    if state_type is not None and not isinstance(sar.state.data, state_type):
                        continue
                    if notary is not None and sar.state.notary != notary:
                        continue
                    if owner_keys is not None:
                        owner = getattr(sar.state.data, "owner", None)
                        key = getattr(owner, "owning_key", owner)
                        if key not in set(owner_keys):
                            continue
                    out.append(sar)
            return out

    def query_by(self, criteria: QueryCriteria | None = None,
                 paging: PageSpecification | None = None,
                 sorting: Sort | None = None) -> Page:
        """Full QueryCriteria engine (reference vaultQueryBy): composable
        criteria + paging + sorting over all vault records. See node.query
        for the criteria classes."""
        with self._lock:
            records = [
                VaultRecord(sar, "unconsumed", self._recorded_time.get(ref),
                            None, self._soft_locks.get(ref))
                for ref, sar in self._unconsumed.items()
            ] + [
                VaultRecord(sar, "consumed", self._recorded_time.get(ref),
                            self._consumed_time.get(ref), None)
                for ref, sar in self._consumed.items()
            ]
        return run_query(records, criteria, paging, sorting)

    # -- soft locking (NodeVaultService :261-296) ----------------------------
    def soft_lock_reserve(self, lock_id: str, refs) -> None:
        with self._lock:
            refs = list(refs)
            for ref in refs:
                holder = self._soft_locks.get(ref)
                if holder is not None and holder != lock_id:
                    raise SoftLockError(
                        f"State {ref} is locked by {holder}")
                if ref not in self._unconsumed:
                    raise SoftLockError(f"State {ref} is not unconsumed")
            for ref in refs:
                self._soft_locks[ref] = lock_id

    def soft_lock_release(self, lock_id: str, refs=None) -> None:
        with self._lock:
            if refs is None:
                for ref in [r for r, holder in self._soft_locks.items()
                            if holder == lock_id]:
                    del self._soft_locks[ref]
            else:
                for ref in refs:
                    if self._soft_locks.get(ref) == lock_id:
                        del self._soft_locks[ref]

    def soft_locked_states(self, lock_id: str | None = None) -> list[StateRef]:
        with self._lock:
            return [r for r, holder in self._soft_locks.items()
                    if lock_id is None or holder == lock_id]

    # -- coin selection (the spend path of OnLedgerAsset) --------------------
    def try_lock_states_for_spending(self, lock_id: str, amount_quantity: int,
                                     state_type: type,
                                     quantity_of=lambda s: s.amount.quantity,
                                     state_filter=None) -> list[StateAndRef]:
        """Greedy selection of unlocked fungible states covering the quantity;
        atomically soft-locks the selection (unconsumedStatesForSpending).
        ``state_filter`` restricts eligibility — e.g. to one currency, so a
        multi-currency vault never pays a USD price in GBP coins."""
        with self._lock:
            selected, total = [], 0
            for sar in self._unconsumed.values():
                if not isinstance(sar.state.data, state_type):
                    continue
                if state_filter is not None and not state_filter(sar.state.data):
                    continue
                if sar.ref in self._soft_locks:
                    continue
                selected.append(sar)
                total += quantity_of(sar.state.data)
                if total >= amount_quantity:
                    break
            if total < amount_quantity:
                return []
            for sar in selected:
                self._soft_locks[sar.ref] = lock_id
            return selected
