"""Notary services: uniqueness (double-spend prevention) + time-window check.

Reference parity (node/services/transactions/ + core NotaryFlow.kt:95-120):
- `UniquenessProvider.commit` with conflict reporting
  (core/node/services/UniquenessProvider.kt, PersistentUniquenessProvider.kt:73-130)
- `SimpleNotaryService` (non-validating) / `ValidatingNotaryService`
  (SimpleNotaryService.kt:12-26, ValidatingNotaryService.kt:38-52)
- `TimeWindowChecker` (services/TimeWindowChecker.kt)

The Raft/BFT clustered backends plug in behind the same `UniquenessProvider`
interface (corda_tpu.consensus, SURVEY.md §7 phase 5).
"""
from __future__ import annotations

import datetime
import logging
import os
import threading
from dataclasses import dataclass

from ..core.contracts.structures import StateRef
from ..core.identity import Party
from ..core.serialization import deserialize, register_type, serialize

_log = logging.getLogger(__name__)


@dataclass(frozen=True)
class ConsumedStateDetails:
    """Who consumed a state, in which transaction (UniquenessProvider.Conflict)."""

    consuming_tx: object     # SecureHash
    consuming_index: int
    requesting_party: str


register_type("notary.ConsumedStateDetails", ConsumedStateDetails)


class UniquenessException(Exception):
    def __init__(self, conflicts: dict):
        super().__init__(f"Input states already consumed: {sorted(conflicts, key=repr)}")
        self.conflicts = conflicts  # StateRef -> ConsumedStateDetails


class UniquenessProvider:
    """The notary commit-log SPI."""

    def commit(self, states: list[StateRef], tx_id, caller: str) -> None:
        raise NotImplementedError


def find_conflicts(consumed_map: dict, states, tx_id) -> dict:
    """All refs already consumed by a DIFFERENT transaction (re-notarising
    the same tx is idempotent) — the shared check of every commit-log
    backend (in-memory, file, replicated)."""
    conflicts = {}
    for ref in states:
        prev = consumed_map.get(ref)
        if prev is not None and prev.consuming_tx != tx_id:
            conflicts[ref] = prev
    return conflicts


def record_all(consumed_map: dict, states, tx_id, caller: str) -> None:
    for i, ref in enumerate(states):
        consumed_map[ref] = ConsumedStateDetails(tx_id, i, caller)


class InMemoryUniquenessProvider(UniquenessProvider):
    """ThreadBox'd map semantics of PersistentUniquenessProvider.kt:73-130:
    atomically check all inputs, record all or none, report ALL conflicts."""

    def __init__(self):
        self._lock = threading.Lock()
        self._consumed: dict[StateRef, ConsumedStateDetails] = {}

    def commit(self, states, tx_id, caller: str) -> None:
        with self._lock:
            conflicts = find_conflicts(self._consumed, states, tx_id)
            if conflicts:
                raise UniquenessException(conflicts)
            record_all(self._consumed, states, tx_id, caller)

    def __len__(self):
        with self._lock:
            return len(self._consumed)


class FileUniquenessProvider(InMemoryUniquenessProvider):
    """Durable commit log: append-only file of canonical-codec records, synced
    before the commit is acknowledged (the JDBC commit-log analog)."""

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        if os.path.exists(path):
            with open(path, "rb") as f:
                for line in f.read().split(b"\n"):
                    if line:
                        ref, details = deserialize(line)
                        self._consumed[ref] = details

    def commit(self, states, tx_id, caller: str) -> None:
        with self._lock:
            conflicts = find_conflicts(self._consumed, states, tx_id)
            if conflicts:
                raise UniquenessException(conflicts)
            with open(self.path, "ab") as f:
                for i, ref in enumerate(states):
                    details = ConsumedStateDetails(tx_id, i, caller)
                    f.write(serialize([ref, details]) + b"\n")
                f.flush()
                os.fsync(f.fileno())
                record_all(self._consumed, states, tx_id, caller)


class TimeWindowChecker:
    """services/TimeWindowChecker.kt: tolerance-adjusted containment of now."""

    def __init__(self, clock=None, tolerance_s: float = 30.0):
        self.clock = clock or (lambda: datetime.datetime.now(datetime.timezone.utc))
        self.tolerance = datetime.timedelta(seconds=tolerance_s)

    def is_valid(self, time_window) -> bool:
        if time_window is None:
            return True
        from ..core.serialization.codec import exact_epoch_micros
        now = exact_epoch_micros(self.clock())
        tol = int(self.tolerance.total_seconds() * 1_000_000)
        # TimeWindow bounds are epoch-microsecond ints (structures.TimeWindow)
        if time_window.until_time is not None and now > time_window.until_time + tol:
            return False
        if time_window.from_time is not None and now < time_window.from_time - tol:
            return False
        return True


class NotaryService:
    """Base notary service installed on a notary node; registers its service
    flow for NotaryFlow.Client inits (TrustedAuthorityNotaryService analog)."""

    type_id = "corda.notary"
    validating = False

    def __init__(self, hub, uniqueness: UniquenessProvider | None = None,
                 time_window_checker: TimeWindowChecker | None = None):
        self.hub = hub
        # back-reference for the node's readiness probe (/readyz checks the
        # commit-log backend — e.g. a raft cluster without a leader is not
        # ready to notarise)
        hub.notary_service = self
        self.uniqueness = uniqueness if uniqueness is not None \
            else InMemoryUniquenessProvider()
        self.time_window_checker = time_window_checker or TimeWindowChecker()

    def install(self, smm) -> None:
        from ..flows.library import NotaryFlow, NotaryServiceFlow
        from ..flows.api import flow_name
        smm.register_flow_factory(
            flow_name(NotaryFlow),
            lambda peer: NotaryServiceFlow(peer, self))

    #: probe-able capability flag (same pattern as the verifier service's
    #: supports_trace_ctx): callers may pass their span context through
    supports_trace_ctx = True

    def _shard_tags(self, refs) -> dict:
        """``{"shards": "s0+s2"}`` when the uniqueness backend partitions
        the ref domain (sharded provider), else nothing — keeps the
        notary.uniqueness span shape unchanged for single-log backends."""
        describe = getattr(self.uniqueness, "touched_shards", None)
        if describe is None:
            return {}
        try:
            return {"shards": describe(refs)}
        except Exception:
            return {}

    def commit(self, input_refs, tx_id, caller_name: str,
               trace_ctx=None) -> None:
        import time as _time

        from ..observability import get_tracer, jlog
        refs = list(input_refs)
        jlog(_log, "notary.commit", ctx=trace_ctx,
             tx_id=tx_id.bytes.hex()[:16], n_inputs=len(refs),
             caller=caller_name)
        with get_tracer().span("notary.commit", parent=trace_ctx,
                               tx_id=tx_id.bytes.hex()[:16],
                               n_inputs=len(refs), caller=caller_name) as sp:
            # notary.uniqueness: the commit-log check itself, separated
            # from request handling so the LEDGER artifact's
            # notary_uniqueness_p99_ms isolates the double-spend check
            # (and, for a replicated provider, the consensus round under
            # its nested raft.commit span) from flow/session overhead
            uctx = sp.context() or trace_ctx
            with get_tracer().span("notary.uniqueness", parent=uctx,
                                   tx_id=tx_id.bytes.hex()[:16],
                                   n_inputs=len(refs),
                                   **self._shard_tags(refs)) as usp:
                kwargs = {}
                if getattr(self.uniqueness, "supports_trace_ctx", False):
                    kwargs["trace_ctx"] = usp.context() or uctx
                    kwargs["metrics"] = getattr(self.hub, "monitoring", None)
                t0 = _time.perf_counter()
                try:
                    self.uniqueness.commit(refs, tx_id, caller_name, **kwargs)
                finally:
                    monitoring = getattr(self.hub, "monitoring", None)
                    if monitoring is not None:
                        trace_id = getattr(uctx, "trace_id", None)
                        monitoring.histogram(
                            "notary_uniqueness_seconds").update(
                                _time.perf_counter() - t0, trace_id=trace_id)

    @property
    def supports_async_commit(self) -> bool:
        """True when the uniqueness backend can group-commit (the raft
        provider's commit_async path) — NotaryServiceFlow parks on the
        returned future instead of blocking the notary node thread for a
        full consensus round per transaction."""
        return hasattr(self.uniqueness, "commit_async")

    def commit_async(self, input_refs, tx_id, caller_name: str,
                     trace_ctx=None):
        """Group-commit path: enqueue on the provider's GroupCommitter and
        return a Future resolving None on commit / failing with
        UniquenessException on conflict. The ``notary.commit`` and
        ``notary.uniqueness`` spans are opened here and finished when the
        verdict lands, so span durations cover the true suspended wait and
        /traces stitching matches the sync path's shape. Returns None when
        the backend has no async path (caller falls back to sync commit)."""
        import time as _time
        from concurrent.futures import Future

        from ..observability import get_tracer, jlog
        if not self.supports_async_commit:
            return None
        refs = list(input_refs)
        jlog(_log, "notary.commit", ctx=trace_ctx,
             tx_id=tx_id.bytes.hex()[:16], n_inputs=len(refs),
             caller=caller_name, group_commit=True)
        tracer = get_tracer()
        sp = tracer.span("notary.commit", parent=trace_ctx,
                         tx_id=tx_id.bytes.hex()[:16], n_inputs=len(refs),
                         caller=caller_name, group_commit=True)
        uctx = sp.context() or trace_ctx
        usp = tracer.span("notary.uniqueness", parent=uctx,
                          tx_id=tx_id.bytes.hex()[:16], n_inputs=len(refs),
                          **self._shard_tags(refs))
        t0 = _time.perf_counter()
        inner = self.uniqueness.commit_async(
            refs, tx_id, caller_name, trace_ctx=usp.context() or uctx,
            metrics=getattr(self.hub, "monitoring", None))
        outer: Future = Future()

        def _done(f):
            err = f.exception()
            monitoring = getattr(self.hub, "monitoring", None)
            if monitoring is not None:
                trace_id = getattr(uctx, "trace_id", None)
                monitoring.histogram("notary_uniqueness_seconds").update(
                    _time.perf_counter() - t0, trace_id=trace_id)
            if err is not None:
                usp.set_tag("error", f"{type(err).__name__}: {err}")
                sp.set_tag("error", f"{type(err).__name__}: {err}")
            usp.finish()
            sp.finish()
            if err is None:
                outer.set_result(None)
            else:
                outer.set_exception(err)

        inner.add_done_callback(_done)
        return outer

    def sign_tx_id(self, tx_id):
        return self.hub.sign(tx_id.bytes)


class SimpleNotaryService(NotaryService):
    """Non-validating: checks uniqueness + time window only
    (SimpleNotaryService.kt:12-26)."""

    type_id = "corda.notary.simple"
    validating = False


class ValidatingNotaryService(NotaryService):
    """Validating: additionally resolves and fully verifies the transaction
    before committing (ValidatingNotaryService.kt:38-52) — on this framework
    the signature checks ride the TPU batcher when the hub's verifier service
    is the TPU one."""

    type_id = "corda.notary.validating"
    validating = True
