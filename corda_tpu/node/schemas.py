"""Custom state persistence schemas: typed projections of vault states.

Reference parity (VERDICT r2 #6):
- ``core/schemas/PersistentTypes.kt``: MappedSchema (a named, versioned set
  of mapped types), PersistentState (a row keyed by StateRef), and the
  QueryableState contract-state interface (supportedSchemas /
  generateMappedObject).
- ``node/services/schema/HibernateObserver.kt``: on every vault update,
  states that support a schema are projected into that schema's table —
  rows appear when a state is produced and disappear when it is consumed.
- ``NodeSchemaService``: the registry of installed schemas.

The TPU-native form: a schema's "table" is an in-memory column store keyed
by StateRef (the same seam the reference fills with Hibernate entities),
exportable as (header, rows) for external consumers, and queryable through
the vault's criteria engine via ``SchemaColumnCriteria``.
"""
from __future__ import annotations

from dataclasses import dataclass, field

from ..core.contracts.structures import StateRef
from .query import ColumnPredicate, _CommonCriteria


@dataclass(frozen=True)
class MappedSchema:
    """A named, versioned projection (PersistentTypes.kt:40-45)."""

    name: str
    version: int
    columns: tuple

    @property
    def table_name(self) -> str:
        return f"{self.name}_v{self.version}"


class QueryableState:
    """Mixin for states exportable to custom schemas (QueryableState in
    PersistentTypes.kt): declare the schemas you support and project
    yourself into a row per schema."""

    def supported_schemas(self) -> tuple:
        raise NotImplementedError

    def generate_mapped_object(self, schema: MappedSchema) -> dict:
        """Return {column: value} for ``schema`` (column set must match
        schema.columns)."""
        raise NotImplementedError


@dataclass(frozen=True)
class PersistentRow:
    """One projected row: the StateRef key + values aligned with the
    schema's columns (PersistentState + PersistentStateRef)."""

    ref: StateRef
    values: tuple


def _queryable(state) -> bool:
    """QueryableState by inheritance OR by shape (dataclass states often
    can't take extra bases; the two methods are the contract)."""
    return isinstance(state, QueryableState) or (
        hasattr(state, "supported_schemas")
        and hasattr(state, "generate_mapped_object"))


class SchemaService:
    """NodeSchemaService + HibernateObserver in one: observes the vault and
    maintains one table per schema. Attach via ``start()`` (the node wires
    this automatically)."""

    def __init__(self, hub):
        self.hub = hub
        self._tables: dict[str, dict[StateRef, PersistentRow]] = {}
        self._schemas: dict[str, MappedSchema] = {}

    def start(self) -> "SchemaService":
        self.hub.vault.add_update_observer(self._on_vault_update)
        return self

    # -- the observer (HibernateObserver.persist) ---------------------------
    def _on_vault_update(self, update) -> None:
        for sar in update.consumed:
            for table in self._tables.values():
                table.pop(sar.ref, None)
        for sar in update.produced:
            state = sar.state.data
            if not _queryable(state):
                continue
            for schema in state.supported_schemas():
                self._schemas.setdefault(schema.table_name, schema)
                row = state.generate_mapped_object(schema)
                values = tuple(row.get(col) for col in schema.columns)
                self._tables.setdefault(schema.table_name, {})[sar.ref] = \
                    PersistentRow(sar.ref, values)

    # -- consumption (the node-schemas export analog) ------------------------
    @property
    def schemas(self) -> list[MappedSchema]:
        return list(self._schemas.values())

    def rows(self, schema: MappedSchema) -> list[PersistentRow]:
        return list(self._tables.get(schema.table_name, {}).values())

    def export_table(self, schema: MappedSchema):
        """(header, rows) for external consumers: header = ("transaction_id",
        "output_index", *columns) — the PersistentStateRef embedded-id shape."""
        header = ("transaction_id", "output_index") + tuple(schema.columns)
        rows = [(r.ref.txhash.bytes.hex(), r.ref.index) + r.values
                for r in self.rows(schema)]
        return header, sorted(rows)


@dataclass(frozen=True)
class SchemaColumnCriteria(_CommonCriteria):
    """Vault query criteria over a custom schema column
    (VaultCustomQueryCriteria's typed-column form): matches states that
    support ``schema`` and whose projected ``column`` satisfies the
    predicate. Composes with And/Or like every other criteria."""

    schema: MappedSchema = None
    column: str = ""
    predicate: ColumnPredicate = field(
        default_factory=lambda: ColumnPredicate("not_null"))
    status: str = "unconsumed"
    participants: tuple | None = None

    def matches(self, rec) -> bool:
        if not self._common_ok(rec):
            return False
        state = rec.sar.state.data
        if not _queryable(state):
            return False
        if self.schema.table_name not in {
                s.table_name for s in state.supported_schemas()}:
            return False
        row = state.generate_mapped_object(self.schema)
        return self.predicate.test(row.get(self.column))
