"""NodeSchedulerService — time-triggered flows from schedulable states.

Reference parity: node/services/events/NodeSchedulerService.kt:44,97,176-212
+ ScheduledActivityObserver: states implementing
`next_scheduled_activity(ref, factory)` get their flow started when the
scheduled instant arrives; consuming the state unschedules it. The clock is
injectable (TestClock semantics) and `wake(now)` is the explicit trigger in
deterministic tests; production wraps it in a timer thread.
"""
from __future__ import annotations

import datetime
import threading

from ..core.contracts.structures import SchedulableState, ScheduledActivity
from ..core.serialization.codec import exact_epoch_micros


class FlowLogicRefFactory:
    """Checkpointable references to flow constructions
    (statemachine/FlowLogicRefFactoryImpl.kt): a (class name, args) pair that
    can be stored inside a state and instantiated later."""

    @staticmethod
    def create(flow_class, *args):
        from ..flows.api import flow_name
        return [flow_name(flow_class), list(args)]

    @staticmethod
    def to_flow_logic(ref):
        from ..node.statemachine import _import_flow_class
        cls = _import_flow_class(ref[0])
        return cls(*ref[1])


class NodeSchedulerService:
    def __init__(self, hub, clock=None):
        self.hub = hub
        self.clock = clock or (lambda: datetime.datetime.now(datetime.timezone.utc))
        self._lock = threading.Lock()
        self._scheduled: dict = {}   # StateRef -> ScheduledActivity

    def start(self) -> None:
        """Observe the vault: produced schedulable states schedule, consumed
        ones unschedule (ScheduledActivityObserver)."""
        self.hub.vault.add_update_observer(self._on_vault_update)

    def _on_vault_update(self, update) -> None:
        with self._lock:
            for sar in update.consumed:
                self._scheduled.pop(sar.ref, None)
            for sar in update.produced:
                state = sar.state.data
                if isinstance(state, SchedulableState):
                    activity = state.next_scheduled_activity(
                        sar.ref, FlowLogicRefFactory)
                    if activity is not None:
                        self._scheduled[sar.ref] = activity

    # -- triggering ----------------------------------------------------------
    def next_deadline_micros(self) -> int | None:
        with self._lock:
            if not self._scheduled:
                return None
            return min(exact_epoch_micros(a.scheduled_at)
                       if hasattr(a.scheduled_at, "tzinfo") else a.scheduled_at
                       for a in self._scheduled.values())

    def wake(self, now: datetime.datetime | None = None) -> list:
        """Fire every activity due at `now` (tests pass a TestClock instant;
        a production timer thread calls this periodically). Returns the
        started state machines."""
        now = now or self.clock()
        now_micros = exact_epoch_micros(now)
        due = []
        with self._lock:
            for ref, activity in list(self._scheduled.items()):
                at = activity.scheduled_at
                at_micros = exact_epoch_micros(at) if hasattr(at, "tzinfo") else at
                if at_micros <= now_micros:
                    due.append((ref, activity))
                    del self._scheduled[ref]
        started = []
        for ref, activity in due:
            flow = FlowLogicRefFactory.to_flow_logic(activity.flow_ref)
            fsm = self.hub.smm.add(flow)
            started.append(fsm)
        return started
