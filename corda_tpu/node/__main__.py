"""Node CLI entry: ``python -m corda_tpu.node --config node.json`` or flags.

Reference parity: NodeStartup.main (node/internal/NodeStartup.kt:1-326) —
parse config, print the banner, start the node, run until interrupted.
"""
from __future__ import annotations

import argparse
import logging
import signal
import sys
import threading

from .node import Node, NodeConfiguration

BANNER = r"""
   ____ ___  ____  ____  _        _____ ____  _   _
  / ___/ _ \|  _ \|  _ \/ \      |_   _|  _ \| | | |
 | |  | | | | |_) | | | | |  _____ | | | |_) | | | |
 | |__| |_| |  _ <| |_| | |_|_____|| | |  __/| |_| |
  \____\___/|_| \_\____/|_____|    |_| |_|    \___/
  distributed ledger, TPU-native
"""


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="corda_tpu.node")
    parser.add_argument("--config", help="JSON NodeConfiguration file")
    parser.add_argument("--name", help="legal name (O=..., L=..., C=..)")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--base-dir", default=".")
    parser.add_argument("--network-map-name")
    parser.add_argument("--network-map-address")
    parser.add_argument("--notary", choices=["simple", "validating"])
    parser.add_argument("--verifier-type", default="InMemory")
    parser.add_argument("--mesh-devices", type=int, default=None,
                        help="with --verifier-type Tpu: shard device "
                             "batches over the first N local chips")
    parser.add_argument("--cordapp", action="append", default=None,
                        help="extra module to load as a cordapp (repeatable)")
    parser.add_argument("--quiet", action="store_true")
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO if not args.quiet else logging.WARN,
                        format="%(asctime)s %(levelname)-5s %(name)s: %(message)s")
    if args.config:
        config = NodeConfiguration.load(args.config)
    else:
        if not args.name:
            parser.error("--name or --config is required")
        config = NodeConfiguration(
            my_legal_name=args.name, host=args.host, port=args.port,
            base_directory=args.base_dir,
            network_map_name=args.network_map_name,
            network_map_address=args.network_map_address,
            notary=args.notary, verifier_type=args.verifier_type,
            mesh_devices=args.mesh_devices)
        if args.cordapp:
            config.cordapps = config.cordapps + args.cordapp

    if not args.quiet:
        print(BANNER)
    node = Node(config).start()
    # the driver greps for this line to know the node is ready
    print(f"NODE READY {node.party.name} {config.host}:{node.messaging.port}",
          flush=True)

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    node.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
