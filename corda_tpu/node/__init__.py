"""Node runtime: state machine manager, service hub, checkpoint storage.

Reference parity: the node "kernel" layer (node/internal/AbstractNode.kt:160+,
services/statemachine/StateMachineManager.kt) rebuilt host-side around the
generator/replay flow model (see corda_tpu.flows).
"""
from .checkpoints import CheckpointStorage, Checkpoint  # noqa: F401
from .services import NodeInfo, ServiceHub, TransactionStorage  # noqa: F401
from .statemachine import StateMachineManager, FlowStateMachine  # noqa: F401
