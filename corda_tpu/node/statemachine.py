"""Flow state machine manager: sessions, suspension, checkpoint-by-replay.

Reference parity (node/services/statemachine/):
- StateMachineManager.add/onSessionMessage/onSessionInit
  (StateMachineManager.kt:307-405, 504-524)
- session message set ported semantically verbatim from SessionMessage.kt:14-41
  (SessionInit/Confirm/Reject/Data/NormalSessionEnd/ErrorSessionEnd)
- restore-and-resume (StateMachineManager.kt:257-305) — here via deterministic
  replay of the checkpointed response log instead of Quasar deserialization
  (design rationale: corda_tpu.flows docstring).

Execution model: flows run cooperatively on the caller's thread until they
block (the single-threaded AffinityExecutor discipline of the reference node,
AbstractNode serverThread — and exactly MockNetwork's deterministic pumping).
"""
from __future__ import annotations

import logging
import queue
import time as _time
import uuid
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Any

from ..core.serialization import deserialize, register_type, serialize
from ..flows.api import (AwaitFuture, ExecuteOnce, FlowException, FlowLogic,
                         FlowSession, FlowTimeoutException, Receive, Send,
                         SendAndReceive, Sleep, UntrustworthyData, Verify,
                         VerifyMany, WaitForLedgerCommit, flow_name,
                         get_initiated_flow_factory)
from ..network.messaging import TOPIC_P2P, TopicSession
from ..observability import get_tracer, jlog
from ..utils.faults import DROP, fault_point
from .checkpoints import Checkpoint, CheckpointStorage, SessionSnapshot

_log = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# Session protocol wire messages (SessionMessage.kt:14-41)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class SessionInit:
    initiator_session_id: int
    initiator_party: str
    flow_name: str
    first_payload: Any = None


@dataclass(frozen=True)
class SessionConfirm:
    initiator_session_id: int
    initiated_session_id: int


@dataclass(frozen=True)
class SessionReject:
    initiator_session_id: int
    error_message: str


@dataclass(frozen=True)
class SessionData:
    recipient_session_id: int
    payload: Any


@dataclass(frozen=True)
class NormalSessionEnd:
    recipient_session_id: int


@dataclass(frozen=True)
class ErrorSessionEnd:
    recipient_session_id: int
    error_message: str


for _cls in (SessionInit, SessionConfirm, SessionReject, SessionData,
             NormalSessionEnd, ErrorSessionEnd):
    register_type(f"session.{_cls.__name__}", _cls)


# ---------------------------------------------------------------------------
# Flow state machine
# ---------------------------------------------------------------------------

class FlowStateMachine:
    """One running flow (FlowStateMachineImpl analog, no fibers)."""

    def __init__(self, run_id: str, flow: FlowLogic, smm: "StateMachineManager"):
        self.run_id = run_id
        self.flow = flow
        self.smm = smm
        self.generator = None
        self.response_log: list = []     # entries: (kind, value)
        self.replay_queue: list = []     # prefix of response_log on restore
        # (session group, peer name) -> session; group 0 = the top-level flow,
        # each @initiating_flow sub-flow gets a deterministic fresh group
        # (FlowLogic.sub_flow) — the reference's (FlowLogic, Party) keying.
        self.sessions: dict[tuple[int, str], FlowSession] = {}
        self.session_group_stack: list = [(0, flow_name(type(flow)))]
        self.session_group_counter: int = 0
        self.parked_on = None            # pending Receive/SendAndReceive/Wait
        self.parked_group: int = 0       # session group active at park time
        self.result_future: Future = Future()
        self.done = False
        # observability: the flow's root span (opened in _register, closed in
        # _finalize); trace_ctx rides into verifier submits and P2P sends
        self.trace_span = None
        self.trace_ctx = None
        # wall-clock stamp of the current external park (Verify /
        # AwaitFuture) — the wait-state span's start once the flow resumes
        self.park_t0 = None

    @property
    def current_group(self) -> tuple[int, str]:
        return self.session_group_stack[-1]

    @property
    def replaying(self) -> bool:
        return bool(self.replay_queue)

    def __repr__(self):
        return f"FlowStateMachine({self.run_id[:8]}, {type(self.flow).__name__})"


class StateMachineManager:
    def __init__(self, service_hub, checkpoint_storage: CheckpointStorage | None = None):
        self.hub = service_hub
        self.checkpoints = checkpoint_storage if checkpoint_storage is not None \
            else CheckpointStorage()
        self.flows: dict[str, FlowStateMachine] = {}
        self._session_index: dict[int, tuple[FlowStateMachine, FlowSession]] = {}
        self._commit_waiters: dict[Any, list[FlowStateMachine]] = {}
        self.changes: list = []  # callbacks: (event, fsm) — RPC feed hook
        # Node-LOCAL initiated-flow factories (a notary's service flows live
        # only on the notary node); falls back to the global @initiated_by
        # registry — AbstractNode.registerInitiatedFlows / installCoreFlows.
        self.flow_factories: dict[str, Any] = {}
        # flow → recorded-transaction mapping (the reference's
        # stateMachineRecordedTransactionMappingFeed source): the hub calls
        # record_tx_mapping while current_fsm identifies the recording flow
        self.current_fsm: FlowStateMachine | None = None
        self.tx_mappings: list[tuple[str, Any]] = []   # (run_id, tx_id)
        self._mapping_observers: list = []
        # Async-completion seam (the Verify suspension point): completions
        # arriving on foreign threads (verifier pool, device batcher) are
        # queued here and executed on the node thread via drain_external().
        # scheduler_poke is installed by the runtime that owns the node
        # thread — the real Node posts drain_external to its SerialExecutor,
        # MockNetwork polls it from run_network().
        self._external: "queue.Queue" = queue.Queue()
        self._awaiting_external = 0
        self.scheduler_poke = None
        # Flow timers (Sleep + Receive timeouts — ClockUtils parity): the
        # clock is injectable (seconds; tests install a TestClock) and
        # timer_driver(delay_s, fire) is how a real-time runtime schedules
        # the wake (the Node wires a threading.Timer that re-enters via the
        # SerialExecutor); deterministic tests advance the clock and call
        # wake_timers() instead. MONOTONIC by default: deadlines are
        # relative, and a wall clock stepping backwards (NTP) would leave a
        # due timer unfired forever.
        self.clock = _time.monotonic
        self.timer_driver = None
        self._timers: list[tuple[float, str, Any]] = []  # (deadline, run_id, request)
        self._next_wake: float | None = None   # soonest scheduled driver wake

    @property
    def awaiting_external(self) -> int:
        """Flows parked on an off-node-thread future (e.g. Verify)."""
        return self._awaiting_external

    def _record_wait(self, fsm: FlowStateMachine, name: str, kind: str,
                     t0, **tags) -> None:
        """Retroactive wait-state span: the time a flow spent parked at a
        commit-path queue, recorded under the flow's root span once the
        wait resolves. ``wait_kind`` makes "time not doing work" first-
        class in the trace tree — observability/critpath.py attributes it
        to a blame component instead of leaving an unexplained gap."""
        if t0 is None or fsm.trace_ctx is None:
            return
        dur = _time.time() - t0
        if dur > 0.0:
            get_tracer().record(name, parent=fsm.trace_ctx, start_s=t0,
                                duration_s=dur, wait_kind=kind, **tags)

    def _post_external(self, fn) -> None:
        """Thread-safe: queue a completion for the node thread."""
        self._external.put(fn)
        poke = self.scheduler_poke
        if poke is not None:
            poke()

    def drain_external(self) -> bool:
        """Run queued async completions. MUST be called on the node thread
        (the real Node's poke hook guarantees it; MockNetwork.run_network
        polls from its single driving thread). Returns True if any ran."""
        ran = False
        while True:
            try:
                fn = self._external.get_nowait()
            except queue.Empty:
                return ran
            ran = True
            fn()

    def record_tx_mapping(self, run_id: str, tx_id) -> None:
        mapping = (run_id, tx_id)
        self.tx_mappings.append(mapping)
        for cb in list(self._mapping_observers):
            try:
                cb(mapping)
            except Exception:
                pass

    def add_mapping_observer(self, cb) -> None:
        self._mapping_observers.append(cb)

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        """Register the P2P handler and restore checkpointed flows
        (StateMachineManager.kt:197-270)."""
        self._p2p_registration = self.hub.network_service.add_message_handler(
            TopicSession(TOPIC_P2P), self._on_message)
        if hasattr(self.hub, "storage"):
            self.hub.storage.add_commit_listener(self._on_tx_committed)
        for cp in self.checkpoints.get_all_checkpoints():
            self._restore(cp)

    def stop(self) -> None:
        """Detach from messaging (node shutdown; checkpoints remain for the
        next start — the restart path of the reference SMM)."""
        reg = getattr(self, "_p2p_registration", None)
        if reg is not None:
            self.hub.network_service.remove_message_handler(reg)
            self._p2p_registration = None

    def add(self, flow: FlowLogic) -> FlowStateMachine:
        """Start a new top-level flow (StateMachineManager.kt:504-524)."""
        fsm = FlowStateMachine(uuid.uuid4().hex, flow, self)
        self._register(fsm)
        self._notify("add", fsm)
        self._start_generator(fsm)
        self._advance(fsm, first=True)
        return fsm

    def _register(self, fsm: FlowStateMachine) -> None:
        # wall-clock anchor for the flow_run commit-path stage histogram
        # (observability/stages.LEDGER_STAGE_METRICS), closed in _finalize
        fsm.started_at = _time.perf_counter()
        monitoring = getattr(self.hub, "monitoring", None)
        if monitoring is not None:   # Flows.StartedPerSecond analog
            monitoring.meter("Flows.Started").mark()
            monitoring.counter("Flows.InFlight").inc()
        audit = getattr(self.hub, "audit", None)
        if audit is not None:
            from .audit import FlowStartEvent
            audit.record_audit_event(FlowStartEvent(
                description="flow started",
                flow_type=flow_name(type(fsm.flow)), flow_id=fsm.run_id))
        tracer = get_tracer()
        if tracer.enabled and fsm.trace_span is None:
            fsm.trace_span = tracer.span(
                "flow.run", parent=fsm.trace_ctx,
                flow_type=flow_name(type(fsm.flow)), flow_id=fsm.run_id)
            fsm.trace_ctx = fsm.trace_span.context()
        jlog(_log, "flow.start", ctx=fsm.trace_ctx,
             flow_type=flow_name(type(fsm.flow)), flow_id=fsm.run_id)
        self.flows[fsm.run_id] = fsm
        fsm.flow.state_machine = fsm
        fsm.flow.service_hub = self.hub

    def _start_generator(self, fsm: FlowStateMachine) -> None:
        gen = fsm.flow.call()
        if not hasattr(gen, "send"):
            # plain function: completed synchronously with its return value
            fsm.generator = None
            self._complete(fsm, gen)
            return
        fsm.generator = gen

    def _notify(self, event: str, fsm: FlowStateMachine) -> None:
        for cb in list(self.changes):
            cb(event, fsm)

    # -- the drive loop ------------------------------------------------------
    def _advance(self, fsm: FlowStateMachine, first: bool = False,
                 resume_value: Any = None, resume_error: Exception | None = None
                 ) -> None:
        """Run the generator until it parks or finishes. Each iteration feeds
        the previous response and receives the next FlowIORequest."""
        previous = self.current_fsm
        self.current_fsm = fsm   # attribute hub.record_transactions to us
        try:
            self._advance_inner(fsm, first, resume_value, resume_error)
        finally:
            self.current_fsm = previous

    def _advance_inner(self, fsm: FlowStateMachine, first: bool = False,
                       resume_value: Any = None,
                       resume_error: Exception | None = None) -> None:
        if fsm.generator is None or fsm.done:
            return
        gen = fsm.generator
        try:
            if first:
                request = next(gen)
            elif resume_error is not None:
                request = gen.throw(resume_error)
            else:
                request = gen.send(resume_value)
        except StopIteration as stop:
            self._complete(fsm, stop.value)
            return
        except Exception as e:
            self._fail(fsm, e)
            return

        while True:
            try:
                if fsm.replaying:
                    action = self._replay_step(fsm, request)
                elif getattr(fsm, "restoring", False):
                    # First live request after replay = the request the flow was
                    # parked on when checkpointed. Its send side already ran
                    # before the restart — only re-arm the wait side.
                    fsm.restoring = False
                    action = self._reexecute_parked(fsm, request)
                else:
                    action = self._execute_request(fsm, request)
            except FlowException as e:
                # session-state errors surface AT THE CALL SITE so flow code
                # (e.g. sendAndReceiveWithRetry) can catch and recover —
                # reference FlowLogic semantics
                fsm.response_log.append(("error", str(e)))
                try:
                    request = gen.throw(e)
                    continue
                except StopIteration as stop:
                    self._complete(fsm, stop.value)
                    return
                except Exception as e2:
                    self._fail(fsm, e2)
                    return
            except Exception as e:
                self._fail(fsm, e)
                return
            if action is _PARK:
                fsm.parked_on = request
                fsm.parked_group = fsm.current_group[0]
                self._arm_timer(fsm, request)
                self._checkpoint(fsm)
                return
            kind, value, error = action
            try:
                if error is not None:
                    request = gen.throw(error)
                else:
                    request = gen.send(value)
            except StopIteration as stop:
                self._complete(fsm, stop.value)
                return
            except Exception as e:
                self._fail(fsm, e)
                return

    def _resume(self, fsm: FlowStateMachine, value: Any = None,
                error: Exception | None = None) -> None:
        if self._timers:
            # any timer armed for the park being resumed is dead: pruning
            # here (a) stops a re-yielded identical request object from
            # inheriting the previous park's deadline and (b) keeps the
            # timer list from accumulating already-resumed flows' entries
            self._timers = [t for t in self._timers if t[1] != fsm.run_id]
        fsm.parked_on = None
        self._advance(fsm, resume_value=value, resume_error=error)

    # -- request execution ---------------------------------------------------
    def _execute_request(self, fsm: FlowStateMachine, request):
        if isinstance(request, Send):
            self._do_send(fsm, request.party, request.payload)
            return self._log(fsm, ("send", None))
        if isinstance(request, SendAndReceive):
            self._do_send(fsm, request.party, request.payload)
            return self._try_receive(fsm, request.party)
        if isinstance(request, Receive):
            self._ensure_session(fsm, request.party, first_payload=None)
            return self._try_receive(fsm, request.party)
        if isinstance(request, WaitForLedgerCommit):
            stx = self.hub.storage.get_transaction(request.tx_id)
            if stx is not None:
                return self._log(fsm, ("commit", request.tx_id))
            self._commit_waiters.setdefault(request.tx_id, []).append(fsm)
            return _PARK
        if isinstance(request, ExecuteOnce):
            return self._log(fsm, ("value", request.producer()))
        if isinstance(request, Verify):
            return self._do_verify(fsm, request)
        if isinstance(request, VerifyMany):
            return self._do_verify_many(fsm, request)
        if isinstance(request, AwaitFuture):
            return self._do_await_future(fsm, request)
        if isinstance(request, Sleep):
            return _PARK        # woken only by its timer (see _arm_timer)
        raise TypeError(f"Flow yielded a non-request value: {request!r}")

    # -- flow timers (Sleep / receive timeouts, ClockUtils parity) -----------
    def _arm_timer(self, fsm: FlowStateMachine, request) -> None:
        if isinstance(request, Sleep):
            delay = max(0.0, float(request.seconds))
        elif isinstance(request, (Receive, SendAndReceive)) and \
                getattr(request, "timeout_s", None) is not None:
            delay = max(0.0, float(request.timeout_s))
        else:
            return
        deadline = self.clock() + delay
        self._timers.append((deadline, fsm.run_id, request))
        self._request_wake(deadline)

    def _request_wake(self, deadline: float) -> None:
        """Schedule ONE driver wake for the soonest deadline (not one OS
        timer per armed request — N concurrent timeouts would mean N live
        threads under Node's threading.Timer driver)."""
        if self.timer_driver is None:
            return
        if self._next_wake is not None and self._next_wake <= deadline:
            return
        self._next_wake = deadline
        self.timer_driver(max(0.0, deadline - self.clock()),
                          self._on_timer_wake)

    def _on_timer_wake(self) -> None:
        self._next_wake = None
        self.wake_timers()
        nxt = self.next_timer_deadline()
        if nxt is not None:
            self._request_wake(nxt)

    def wake_timers(self, now: float | None = None) -> int:
        """Fire every due timer (node thread). Stale timers — their flow
        already resumed, failed, or parked on a LATER request — are dropped
        by the identity check against the live parked request."""
        now = self.clock() if now is None else now
        due = [t for t in self._timers if t[0] <= now]
        if not due:
            return 0
        self._timers = [t for t in self._timers if t[0] > now]
        fired = 0
        for _, run_id, request in due:
            fsm = self.flows.get(run_id)
            if fsm is None or fsm.done or fsm.parked_on is not request:
                continue
            fired += 1
            if isinstance(request, Sleep):
                fsm.response_log.append(("value", None))
                self._resume(fsm, value=None)
            else:
                err = FlowTimeoutException(
                    f"Timed out after {request.timeout_s}s waiting for "
                    f"{request.party.name}")
                fsm.response_log.append(("error", _error_payload(err)))
                self._resume(fsm, error=err)
        return fired

    def next_timer_deadline(self) -> float | None:
        return min((t[0] for t in self._timers), default=None)

    def _do_verify(self, fsm: FlowStateMachine, request: Verify):
        """The Verify suspension point (FlowStateMachineImpl.kt:379-393): park
        the flow on the configured TransactionVerifierService's future and
        resume it on the node thread when the future resolves — so Tpu /
        OutOfProcess backends verify off the node thread and N suspended
        flows' signatures coalesce into shared device batches. Without an
        async-capable service the verification runs synchronously here (the
        no-service fallback of Services.kt)."""
        svc = self.hub.verifier_service
        if svc is None or not hasattr(svc, "verify_signed"):
            try:
                request.stx.verify(
                    self.hub,
                    check_sufficient_signatures=request.check_sufficient_signatures)
            except Exception as e:
                # same yield-site contract as the async path: the failure is
                # thrown INTO the flow with its type preserved (a flow may
                # catch SignatureException and recover), not routed to _fail
                return self._log(fsm, ("error", _error_payload(e)))
            return self._log(fsm, ("value", None))
        kwargs = {}
        if getattr(svc, "supports_trace_ctx", False) and fsm.trace_ctx is not None:
            kwargs["trace_ctx"] = fsm.trace_ctx
        fut = svc.verify_signed(
            request.stx, self.hub,
            check_sufficient_signatures=request.check_sufficient_signatures,
            **kwargs)
        self._awaiting_external += 1
        fsm.park_t0 = _time.time()
        fut.add_done_callback(
            lambda f: self._post_external(
                lambda: self._on_verify_done(fsm, f, request)))
        return _PARK

    def _on_verify_done(self, fsm: FlowStateMachine, fut: Future,
                        request: Verify) -> None:
        """Node-thread continuation of a Verify park (via drain_external)."""
        self._awaiting_external -= 1
        if fsm.done or fsm.run_id not in self.flows:
            return   # flow failed/completed meanwhile (e.g. session error)
        if fsm.parked_on is not request:
            # Same identity guard as wake_timers: a stale or duplicate
            # future completion (double-invoked callback, flow already
            # resumed by another path) must not resume at the wrong yield.
            return
        self._record_wait(fsm, "wait.verify_park", "verify.park",
                          fsm.park_t0)
        err = fut.exception()
        if err is None:
            fsm.response_log.append(("value", None))
            self._resume(fsm, value=None)
        else:
            # the log records the type too, so a flow that CAUGHT this
            # error and continued replays identically after a restart
            fsm.response_log.append(("error", _error_payload(err)))
            self._resume(fsm, error=err)

    def _do_verify_many(self, fsm: FlowStateMachine, request: VerifyMany):
        """One yield site, N verifier submissions: the whole wave of a
        dependency-resolution frontier lands in the batcher concurrently
        (the group-commit analog on the verify side). Resumes with None
        when every verification succeeds; the first failure in submission
        order is thrown at the yield site. A node without an async
        verifier service falls back to verifying the wave synchronously."""
        stxs = list(request.stxs)
        if not stxs:
            return self._log(fsm, ("value", None))
        svc = self.hub.verifier_service
        if svc is None or not hasattr(svc, "verify_signed"):
            for stx in stxs:
                try:
                    stx.verify(self.hub, check_sufficient_signatures=
                               request.check_sufficient_signatures)
                except Exception as e:
                    return self._log(fsm, ("error", _error_payload(e)))
            return self._log(fsm, ("value", None))
        kwargs = {}
        if getattr(svc, "supports_trace_ctx", False) and fsm.trace_ctx is not None:
            kwargs["trace_ctx"] = fsm.trace_ctx
        futs = [svc.verify_signed(
                    stx, self.hub, check_sufficient_signatures=
                    request.check_sufficient_signatures, **kwargs)
                for stx in stxs]
        # ONE external-wait slot for the whole wave: the flow resumes once,
        # when the slowest member resolves
        self._awaiting_external += 1
        state = {"remaining": len(futs), "errors": {},
                 "n": len(futs), "t0": _time.time()}
        for i, fut in enumerate(futs):
            fut.add_done_callback(
                lambda f, i=i: self._post_external(
                    lambda: self._on_verify_many_one(fsm, f, i, state,
                                                     request)))
        return _PARK

    def _on_verify_many_one(self, fsm: FlowStateMachine, fut: Future,
                            index: int, state: dict,
                            request: VerifyMany) -> None:
        """Node-thread continuation for ONE member of a VerifyMany wave;
        the last arrival resumes the flow."""
        err = fut.exception()
        if err is not None:
            state["errors"][index] = err
        state["remaining"] -= 1
        if state["remaining"] > 0:
            return
        self._awaiting_external -= 1
        if fsm.done or fsm.run_id not in self.flows:
            return
        if fsm.parked_on is not request:
            return
        self._record_wait(fsm, "wait.verify_gather", "verify.gather",
                          state["t0"], wave=state["n"])
        if state["errors"]:
            first = state["errors"][min(state["errors"])]
            fsm.response_log.append(("error", _error_payload(first)))
            self._resume(fsm, error=first)
        else:
            fsm.response_log.append(("value", None))
            self._resume(fsm, value=None)

    def _do_await_future(self, fsm: FlowStateMachine, request: AwaitFuture):
        """Generic park-on-a-future (the notary-wait suspension point for
        the group-commit path): the producer runs on the node thread and
        returns a Future; the flow parks until it resolves and resumes
        with its result (which must be checkpoint-serializable) or its
        exception, type preserved across replay."""
        fut = request.producer()
        if fut is None:
            return self._log(fsm, ("value", None))
        if fut.done():   # fast path — no external wait, no extra drain turn
            err = fut.exception()
            if err is None:
                return self._log(fsm, ("value", fut.result()))
            return self._log(fsm, ("error", _error_payload(err)))
        self._awaiting_external += 1
        fsm.park_t0 = _time.time()
        fut.add_done_callback(
            lambda f: self._post_external(
                lambda: self._on_await_done(fsm, f, request)))
        return _PARK

    def _on_await_done(self, fsm: FlowStateMachine, fut: Future,
                       request: AwaitFuture) -> None:
        """Node-thread continuation of an AwaitFuture park."""
        self._awaiting_external -= 1
        if fsm.done or fsm.run_id not in self.flows:
            return
        if fsm.parked_on is not request:
            return
        self._record_wait(fsm, "wait.await_future",
                          getattr(request, "purpose", "future"),
                          fsm.park_t0)
        err = fut.exception()
        if err is None:
            fsm.response_log.append(("value", fut.result()))
            self._resume(fsm, value=fut.result())
        else:
            fsm.response_log.append(("error", _error_payload(err)))
            self._resume(fsm, error=err)

    def _log(self, fsm: FlowStateMachine, entry):
        """Append to the response log and produce the resume action."""
        fsm.response_log.append(entry)
        kind, value = entry
        if kind == "send":
            return (kind, None, None)
        if kind == "data":
            return (kind, UntrustworthyData(value), None)
        if kind == "value":
            return (kind, value, None)
        if kind == "commit":
            return (kind, self.hub.storage.get_transaction(value), None)
        if kind == "error":
            return (kind, None, _rebuild_error(value))
        raise AssertionError(entry)

    def _reexecute_parked(self, fsm: FlowStateMachine, request):
        """Re-arm a request that was pending when the checkpoint was written:
        receives re-check the (restored) inbound queue; ledger waits re-check
        storage; sends never park so never appear here."""
        if isinstance(request, (Receive, SendAndReceive)):
            return self._try_receive(fsm, request.party)
        return self._execute_request(fsm, request)

    def _replay_step(self, fsm: FlowStateMachine, request):
        """Consume one recorded response instead of performing IO
        (restore-and-resume: the IO already happened before the restart)."""
        entry = fsm.replay_queue.pop(0)
        kind, value = entry
        if kind == "send":
            return (kind, None, None)
        if kind == "data":
            return (kind, UntrustworthyData(value), None)
        if kind == "value":
            return (kind, value, None)
        if kind == "commit":
            return (kind, self.hub.storage.get_transaction(value), None)
        if kind == "error":
            return (kind, None, _rebuild_error(value))
        raise AssertionError(entry)

    def _try_receive(self, fsm: FlowStateMachine, party):
        sess = fsm.sessions[(fsm.current_group[0], str(party.name))]
        if sess.received:
            payload = sess.received.pop(0)
            return self._log(fsm, ("data", payload))
        if sess.error is not None:
            err, sess.error = sess.error, None
            sess.state = "ended"  # the session is dead; later receives must
            return self._log(fsm, ("error", str(err)))  # fail, not hang
        if sess.state in ("ended", "errored"):
            return self._log(fsm, ("error",
                                   f"Session with {party.name} has ended"))
        return _PARK

    # -- session plumbing ----------------------------------------------------
    def _ensure_session(self, fsm: FlowStateMachine, party,
                        first_payload) -> FlowSession:
        group, initiator_name = fsm.current_group
        key = (group, str(party.name))
        sess = fsm.sessions.get(key)
        if sess is not None:
            return sess
        sess = FlowSession(peer=party)
        sess.group = group
        fsm.sessions[key] = sess
        self._session_index[sess.our_session_id] = (fsm, sess)
        init = SessionInit(sess.our_session_id,
                           str(self.hub.my_info.legal_identity.name),
                           initiator_name, first_payload)
        self._post(party, init)
        sess._init_payload_sent = first_payload is not None
        return sess

    def _do_send(self, fsm: FlowStateMachine, party, payload) -> None:
        sess = fsm.sessions.get((fsm.current_group[0], str(party.name)))
        if sess is None:
            self._ensure_session(fsm, party, first_payload=payload)
            return
        if sess.state == "initiating":
            if not hasattr(sess, "pending_out"):
                sess.pending_out = []
            sess.pending_out.append(payload)
            return
        if sess.state in ("ended", "errored"):
            raise FlowException(f"Session with {party.name} is {sess.state}")
        self._post(party, SessionData(sess.peer_session_id, payload))

    def _post(self, party, message) -> None:
        svc = self.hub.network_service
        fsm = self.current_fsm
        if getattr(svc, "supports_trace", False) and fsm is not None \
                and fsm.trace_ctx is not None:
            ctx = fsm.trace_ctx
            # ctx is a SpanContext once _register ran under a live tracer,
            # but may still be the raw wire tuple of an initiating message
            ids = ctx if isinstance(ctx, tuple) else (ctx.trace_id, ctx.span_id)
            get_tracer().record(
                "session.send", parent=ctx, peer=str(party.name),
                kind=type(message).__name__)
            svc.send(TopicSession(TOPIC_P2P), serialize(message),
                     str(party.name), trace=ids)
            return
        svc.send(TopicSession(TOPIC_P2P), serialize(message), str(party.name))

    def on_peer_unreachable(self, peer_name: str) -> None:
        """Transport-level delivery failure (the TCP plane's
        on_send_failure hook): every live session toward that peer errors,
        waking parked flows with a FlowException at their yield site — the
        analog of the reference's undeliverable-message surfacing. Without
        this a flow awaiting a dead peer's reply parks forever."""
        for fsm in list(self.flows.values()):
            for sess in list(fsm.sessions.values()):
                if str(sess.peer.name) != str(peer_name) or \
                        sess.state in ("ended", "errored"):
                    continue
                sess.state = "errored"
                sess.error = FlowException(
                    f"peer {peer_name} is unreachable")
                self._maybe_deliver(fsm, sess)

    # -- inbound dispatch (onSessionMessage, StateMachineManager.kt:307+) ----
    def _on_message(self, msg) -> None:
        sm = deserialize(msg.data)
        trace = getattr(msg, "trace", None)
        if trace is not None:
            get_tracer().record("session.receive", parent=trace,
                                sender=str(getattr(msg, "sender", None)),
                                kind=type(sm).__name__)
        if isinstance(sm, SessionInit):
            self._on_session_init(sm, trace=trace)
            return
        if isinstance(sm, SessionConfirm):
            entry = self._session_index.get(sm.initiator_session_id)
            if entry is None:
                return
            fsm, sess = entry
            sess.peer_session_id = sm.initiated_session_id
            sess.state = "open"
            for payload in getattr(sess, "pending_out", []):
                self._post(sess.peer, SessionData(sess.peer_session_id, payload))
            if hasattr(sess, "pending_out"):
                sess.pending_out = []
            return
        entry = self._session_index.get(sm.recipient_session_id
                                        if not isinstance(sm, SessionReject)
                                        else sm.initiator_session_id)
        if entry is None:
            return
        fsm, sess = entry
        if isinstance(sm, SessionReject):
            sess.state = "errored"
            sess.error = FlowException(sm.error_message)
        elif isinstance(sm, SessionData):
            sess.received.append(sm.payload)
        elif isinstance(sm, NormalSessionEnd):
            sess.state = "ended"
        elif isinstance(sm, ErrorSessionEnd):
            sess.state = "errored"
            sess.error = FlowException(sm.error_message)
        self._maybe_deliver(fsm, sess)

    def _maybe_deliver(self, fsm: FlowStateMachine, sess: FlowSession) -> None:
        req = fsm.parked_on
        if req is None or not isinstance(req, (Receive, SendAndReceive)):
            return
        if str(req.party.name) != str(sess.peer.name):
            return
        if fsm.parked_group != getattr(sess, "group", 0):
            return  # data for a different sub-flow's session
        if sess.received:
            payload = sess.received.pop(0)
            fsm.response_log.append(("data", payload))
            self._resume(fsm, value=UntrustworthyData(payload))
        elif sess.error is not None:
            err, sess.error = sess.error, None
            sess.state = "ended"
            fsm.response_log.append(("error", str(err)))
            self._resume(fsm, error=FlowException(str(err)))
        elif sess.state == "ended":
            msg = f"Session with {sess.peer.name} has ended"
            fsm.response_log.append(("error", msg))
            self._resume(fsm, error=FlowException(msg))

    def register_flow_factory(self, initiator_name: str, factory) -> None:
        self.flow_factories[initiator_name] = factory

    def discard_session(self, fsm: FlowStateMachine, group: int,
                        party_name: str) -> None:
        """Forget a (dead) session entirely — including its inbound-routing
        index entry, so a late message on the old session id can never reach
        the flow again (the retry helper's fresh-session semantics).

        No-op during checkpoint replay: the logged error that triggered the
        original discard is being replayed from the response log, but the
        session in the table is the *restored* (live) one — popping it would
        orphan the flow's later exchanges with the same party (same principle
        as ExecuteOnce: side effects must not re-run during replay)."""
        if fsm.replaying:
            return
        sess = fsm.sessions.pop((group, party_name), None)
        if sess is not None:
            self._session_index.pop(sess.our_session_id, None)

    def _on_session_init(self, init: SessionInit,
                         trace: tuple | None = None) -> None:
        factory = (self.flow_factories.get(init.flow_name)
                   or get_initiated_flow_factory(init.flow_name))
        peer = self.hub.well_known_party(init.initiator_party)
        if factory is None or peer is None:
            reason = (f"No initiated flow registered for {init.flow_name}"
                      if factory is None else
                      f"Unknown party {init.initiator_party}")
            if peer is not None:
                self._post(peer, SessionReject(init.initiator_session_id, reason))
            return
        flow = factory(peer)
        fsm = FlowStateMachine(uuid.uuid4().hex, flow, self)
        # the responder flow's span joins the initiator's trace — the wire
        # carried (trace_id, span_id), so the whole P2P exchange is one trace
        fsm.trace_ctx = trace
        self._register(fsm)
        sess = FlowSession(peer=peer,
                           peer_session_id=init.initiator_session_id,
                           state="open")
        sess.group = 0  # the responder's top-level session
        fsm.sessions[(0, str(peer.name))] = sess
        self._session_index[sess.our_session_id] = (fsm, sess)
        if init.first_payload is not None:
            sess.received.append(init.first_payload)
        self._post(peer, SessionConfirm(init.initiator_session_id,
                                        sess.our_session_id))
        self._notify("add", fsm)
        self._start_generator(fsm)
        self._advance(fsm, first=True)

    # -- ledger-commit wakeups ----------------------------------------------
    def _on_tx_committed(self, stx) -> None:
        for fsm in self._commit_waiters.pop(stx.id, []):
            fsm.response_log.append(("commit", stx.id))
            self._resume(fsm, value=stx)

    # -- completion ----------------------------------------------------------
    def _complete(self, fsm: FlowStateMachine, result) -> None:
        fsm.done = True
        self._end_sessions(fsm, error=None)
        self._finalize(fsm)
        fsm.result_future.set_result(result)
        self._notify("remove", fsm)

    def _fail(self, fsm: FlowStateMachine, error: Exception) -> None:
        fsm.done = True
        audit = getattr(self.hub, "audit", None)
        if audit is not None:
            from .audit import FlowErrorAuditEvent
            audit.record_audit_event(FlowErrorAuditEvent(
                description="flow failed",
                flow_type=flow_name(type(fsm.flow)), flow_id=fsm.run_id,
                error=f"{type(error).__name__}: {error}"))
        self._end_sessions(fsm, error=error)
        self._finalize(fsm)
        fsm.result_future.set_exception(error)
        self._notify("remove", fsm)

    def _finalize(self, fsm: FlowStateMachine) -> None:
        if fsm.trace_span is not None:
            fsm.trace_span.finish()
            fsm.trace_span = None
        jlog(_log, "flow.end", ctx=fsm.trace_ctx,
             flow_type=flow_name(type(fsm.flow)), flow_id=fsm.run_id)
        monitoring = getattr(self.hub, "monitoring", None)
        if monitoring is not None and fsm.run_id in self.flows:
            monitoring.meter("Flows.Finished").mark()
            monitoring.counter("Flows.InFlight").dec()
            started = getattr(fsm, "started_at", None)
            if started is not None:
                trace_id = getattr(fsm.trace_ctx, "trace_id", None)
                monitoring.histogram("flow_run_seconds").update(
                    _time.perf_counter() - started, trace_id=trace_id)
        # crash-consistency seam: a "drop" rule here models a process kill
        # AFTER the flow's sends went out but BEFORE the checkpoint was
        # removed — the surviving artifact of exactly that crash window.
        # Restart must replay the checkpoint idempotently (no re-sends).
        if fault_point("smm.checkpoint_remove", detail=fsm.run_id) != DROP:
            self.checkpoints.remove_checkpoint(fsm.run_id)
        self.flows.pop(fsm.run_id, None)
        self._cleanup_sessions(fsm)
        # auto-release any vault soft locks held under this flow's id —
        # VaultSoftLockManager parity (locks must not outlive their flow)
        vault = getattr(self.hub, "vault", None)
        if vault is not None:
            vault.soft_lock_release(fsm.run_id)

    def _end_sessions(self, fsm: FlowStateMachine, error) -> None:
        for sess in fsm.sessions.values():
            if sess.state not in ("open", "initiating") or sess.peer_session_id is None:
                continue
            if error is None:
                self._post(sess.peer, NormalSessionEnd(sess.peer_session_id))
            else:
                self._post(sess.peer,
                           ErrorSessionEnd(sess.peer_session_id, str(error)))

    def _cleanup_sessions(self, fsm: FlowStateMachine) -> None:
        for sess in fsm.sessions.values():
            self._session_index.pop(sess.our_session_id, None)

    # -- checkpointing -------------------------------------------------------
    def _checkpoint(self, fsm: FlowStateMachine) -> None:
        """Atomic checkpoint at suspension (updateCheckpoint,
        StateMachineManager.kt:526-543)."""
        fields = {k: v for k, v in vars(fsm.flow).items()
                  if k not in ("state_machine", "service_hub")}
        sessions = [SessionSnapshot(
            peer_name=str(s.peer.name), our_session_id=s.our_session_id,
            peer_session_id=s.peer_session_id, state=s.state,
            received=list(s.received),
            pending_out=list(getattr(s, "pending_out", [])),
            group=getattr(s, "group", 0))
            for s in fsm.sessions.values()]
        cp = Checkpoint(run_id=fsm.run_id,
                        flow_class=flow_name(type(fsm.flow)),
                        flow_fields=fields,
                        response_log=list(fsm.response_log),
                        sessions=sessions)
        self.checkpoints.add_checkpoint(cp)

    def _restore(self, cp: Checkpoint) -> None:
        """Rebuild a flow from its checkpoint and replay it to its suspension
        point (restoreFibersFromCheckpoints semantics via replay)."""
        cls = _import_flow_class(cp.flow_class)
        flow = cls.__new__(cls)
        for k, v in cp.flow_fields.items():
            setattr(flow, k, v)
        fsm = FlowStateMachine(cp.run_id, flow, self)
        fsm.response_log = list(cp.response_log)
        fsm.replay_queue = list(cp.response_log)
        self._register(fsm)
        for snap in cp.sessions:
            peer = self.hub.well_known_party(snap.peer_name)
            sess = FlowSession(peer=peer, our_session_id=snap.our_session_id,
                               peer_session_id=snap.peer_session_id,
                               state=snap.state, received=list(snap.received))
            sess.pending_out = list(snap.pending_out)
            sess.group = snap.group
            fsm.sessions[(snap.group, snap.peer_name)] = sess
            self._session_index[sess.our_session_id] = (fsm, sess)
        fsm.restoring = True
        self._notify("add", fsm)
        self._start_generator(fsm)
        self._advance(fsm, first=True)


class FlowScheduler:
    """Bounded-concurrency flow launcher for one node — the cooperative
    multi-flow discipline (reference: thousands of Quasar fibers per node,
    PAPER.md L5b). Flows already interleave on the node thread by parking
    at send/receive/verify/notary-wait; what serialized them was the
    caller launching one flow and joining it end-to-end. The scheduler
    keeps up to ``max_concurrent`` flows in flight so a node continuously
    feeds the verifier batcher's and the GroupCommitter's bulk classes.

    Node-thread only: ``submit`` enqueues a factory and returns a proxy
    Future; each completion launches the next waiter via the external
    queue (never recursively inside the finishing flow's stack), so
    MockNetwork pumping and checkpoint replay stay deterministic."""

    def __init__(self, smm: StateMachineManager, max_concurrent: int = 8):
        self.smm = smm
        self.max_concurrent = max_concurrent
        self._waiting: list = []      # (flow factory, proxy, submit wall ts)
        self._in_flight = 0
        self.high_water = 0           # max concurrent in-flight observed
        self.launched = 0

    @property
    def in_flight(self) -> int:
        return self._in_flight

    @property
    def waiting(self) -> int:
        return len(self._waiting)

    def submit(self, flow_factory) -> Future:
        """Queue a flow for launch; returns a Future mirroring the flow's
        result_future (result or exception)."""
        proxy: Future = Future()
        self._waiting.append((flow_factory, proxy, _time.time()))
        self._pump()
        return proxy

    def _pump(self) -> None:
        while self._waiting and self._in_flight < self.max_concurrent:
            factory, proxy, t_sub = self._waiting.pop(0)
            self._in_flight += 1
            self.launched += 1
            if self._in_flight > self.high_water:
                self.high_water = self._in_flight
            try:
                fsm = self.smm.add(factory())
            except Exception as e:
                self._in_flight -= 1
                proxy.set_exception(e)
                continue
            # admission wait: submit-to-launch time spent in _waiting. The
            # flow's root span only exists from launch, so the wait span
            # is recorded retroactively, starting BEFORE its parent — the
            # critical-path extractor prepends it to the blocking chain.
            self.smm._record_wait(fsm, "wait.scheduler_admission",
                                  "scheduler.admission", t_sub)
            fsm.result_future.add_done_callback(
                lambda f, proxy=proxy: self._on_done(f, proxy))

    def _on_done(self, fut: Future, proxy: Future) -> None:
        # result_future resolves on the node thread (_complete/_fail), so
        # launching the next waiter here would recursively advance a new
        # flow inside the finishing flow's stack — defer the pump through
        # the external queue to keep the drive loop's discipline
        self._in_flight -= 1
        err = fut.exception()
        if err is None:
            proxy.set_result(fut.result())
        else:
            proxy.set_exception(err)
        if self._waiting:
            self.smm._post_external(self._pump)


_PARK = object()


def _error_payload(exc: Exception):
    """Checkpointable encoding of a flow-visible error that preserves the
    TYPE across replay: flows legitimately catch specific exceptions
    (FlowTimeoutException, SignatureException from Verify) and continue —
    replaying them as bare FlowException would make a recovered flow
    diverge after a restart. Plain FlowExceptions stay strings (legacy
    log-entry format, still accepted by _rebuild_error)."""
    if type(exc) is FlowException:
        return str(exc)
    return [f"{type(exc).__module__}:{type(exc).__qualname__}", str(exc)]


#: Modules whose Exception types may be reconstructed from a checkpoint
#: log. A fixed list (not a dynamic import of whatever 'module:qualname'
#: the payload names): checkpoint storage or a session error must not be
#: able to trigger arbitrary import side effects or invoke arbitrary
#: one-string-arg callables — mirrors the reference's checkpoint class
#: restrictions (CheckpointSerializationScheme).
_ERROR_MODULES = (
    "corda_tpu.flows.api",
    "corda_tpu.flows.library",
    "corda_tpu.flows.state_replacement",
    "corda_tpu.flows.contract_upgrade",
    "corda_tpu.core.contracts.exceptions",
    "corda_tpu.core.crypto.signatures",
    "corda_tpu.core.crypto.merkle",
    "corda_tpu.core.transactions.signed",
    "corda_tpu.core.serialization.codec",
    "corda_tpu.node.notary",
)
_ERROR_REGISTRY: dict[str, type] | None = None


def _error_registry() -> dict[str, type]:
    global _ERROR_REGISTRY
    if _ERROR_REGISTRY is None:
        import importlib

        reg: dict[str, type] = {}
        for mod_name in _ERROR_MODULES:
            mod = importlib.import_module(mod_name)
            for obj in vars(mod).values():
                # defining module only — re-exports register under their
                # home module, matching _error_payload's encoding
                if (isinstance(obj, type) and issubclass(obj, Exception)
                        and obj.__module__ == mod_name):
                    reg[f"{mod_name}:{obj.__qualname__}"] = obj
        for obj in (ValueError, KeyError, RuntimeError, TimeoutError):
            reg[f"builtins:{obj.__qualname__}"] = obj
        _ERROR_REGISTRY = reg
    return _ERROR_REGISTRY


def _rebuild_error(payload) -> Exception:
    if isinstance(payload, str):
        return FlowException(payload)
    type_path, msg = payload
    cls = _error_registry().get(type_path)
    if cls is None:
        return FlowException(msg)
    try:
        return cls(msg)
    except Exception:
        return FlowException(msg)


def _import_flow_class(name: str) -> type:
    import importlib

    # flow_name() produces module.QualName where QualName may be dotted
    parts = name.split(".")
    for split in range(len(parts) - 1, 0, -1):
        try:
            mod = importlib.import_module(".".join(parts[:split]))
        except ImportError:
            continue
        obj = mod
        try:
            for attr in parts[split:]:
                obj = getattr(obj, attr)
            return obj
        except AttributeError:
            continue
    raise ImportError(f"Cannot resolve flow class {name!r}")
