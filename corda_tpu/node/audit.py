"""Audit service: the typed audit-event taxonomy + recording service.

Reference parity: services/api/AuditService.kt:14-93 — the sealed AuditEvent
hierarchy (FlowAppAuditEvent, FlowPermissionAuditEvent, FlowProgressAuditEvent,
FlowErrorAuditEvent, SystemAuditEvent) and the AuditService SPI the node
records into. The reference ships this as a skeleton (events defined, an
in-memory recorder); here the node actually records flow lifecycle +
permission decisions (see StateMachineManager and CordaRPCOps call sites).
"""
from __future__ import annotations

import datetime
import threading
from dataclasses import dataclass, field
from typing import Any, Callable


def _now() -> datetime.datetime:
    return datetime.datetime.now(datetime.timezone.utc)


@dataclass(frozen=True)
class AuditEvent:
    """Base of all audit events (AuditService.kt AuditEvent)."""

    description: str
    principal: str = "node"
    context: dict = field(default_factory=dict)
    timestamp: datetime.datetime = field(default_factory=_now)


@dataclass(frozen=True)
class FlowAuditEvent(AuditEvent):
    """An event tied to one flow instance (FlowAppAuditEvent shape)."""

    flow_type: str = ""
    flow_id: str = ""


@dataclass(frozen=True)
class FlowStartEvent(FlowAuditEvent):
    pass


@dataclass(frozen=True)
class FlowProgressAuditEvent(FlowAuditEvent):
    """Progress-tracker step transition (FlowProgressAuditEvent)."""

    step: str = ""


@dataclass(frozen=True)
class FlowErrorAuditEvent(FlowAuditEvent):
    error: str = ""


@dataclass(frozen=True)
class FlowPermissionAuditEvent(FlowAuditEvent):
    """A permission check on starting/operating a flow
    (FlowPermissionAuditEvent: permissionRequested/permissionGranted)."""

    permission_requested: str = ""
    permission_granted: bool = False


@dataclass(frozen=True)
class SystemAuditEvent(AuditEvent):
    pass


class AuditService:
    """SPI: record one event. The node default keeps an in-memory log with
    observer callbacks (the persistence backend is a storage concern, same
    stance as the reference's skeleton)."""

    def record_audit_event(self, event: AuditEvent) -> None:
        raise NotImplementedError


class InMemoryAuditService(AuditService):
    def __init__(self, capacity: int = 10_000):
        self._lock = threading.Lock()
        self._events: list[AuditEvent] = []
        self._capacity = capacity
        self._observers: list[Callable[[AuditEvent], Any]] = []

    def record_audit_event(self, event: AuditEvent) -> None:
        with self._lock:
            self._events.append(event)
            if len(self._events) > self._capacity:
                del self._events[: len(self._events) - self._capacity]
            observers = list(self._observers)
        for cb in observers:
            cb(event)

    def add_observer(self, cb: Callable[[AuditEvent], Any]) -> None:
        with self._lock:
            self._observers.append(cb)

    def events(self, of_type: type | None = None) -> list[AuditEvent]:
        with self._lock:
            evs = list(self._events)
        if of_type is not None:
            evs = [e for e in evs if isinstance(e, of_type)]
        return evs
