"""RPC operations surface — the client-visible node API.

Reference parity: CordaRPCOps (core/messaging/CordaRPCOps.kt:60-449, 54 ops)
and CordaRPCOpsImpl (node/internal/CordaRPCOpsImpl.kt:1-199). The wire
transport (queue-backed proxy with observable demux, RPCApi.kt/RPCServer.kt)
plugs in behind this object; in-process callers (shell, tests, webserver
equivalent) call it directly.

Streaming (`DataFeed`) follows the reference shape: a snapshot plus a
subscription handle; updates are delivered to registered callbacks (the Rx
Observable analog on the deterministic host runtime).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

from ..flows.api import FlowLogic, rpc_startable_flows, flow_name


@dataclass
class DataFeed:
    """snapshot + live updates (CordaRPCOps DataFeed)."""

    snapshot: Any
    _subscribe: Callable[[Callable], None]

    def subscribe(self, callback: Callable) -> None:
        self._subscribe(callback)


@dataclass(frozen=True)
class StateMachineInfo:
    run_id: str
    flow_class: str
    done: bool


from ..core.serialization import register_type as _register_type  # noqa: E402

_register_type("rpc.StateMachineInfo", StateMachineInfo)


class FlowPermissionException(Exception):
    pass


class CordaRPCOps:
    """The operation set served to clients (CordaRPCOps.kt:60+)."""

    def __init__(self, hub, smm):
        self.hub = hub
        self.smm = smm

    # -- node / network info -------------------------------------------------
    def node_identity(self):
        return self.hub.my_info

    def network_map_snapshot(self) -> list:
        return self.hub.network_map_cache.all_nodes()

    def notary_identities(self) -> list:
        return [n.notary_identity for n in self.hub.network_map_cache.notary_nodes()]

    def current_node_time(self):
        import datetime
        return datetime.datetime.now(datetime.timezone.utc)

    # -- flows ---------------------------------------------------------------
    def registered_flows(self) -> list[str]:
        return sorted(rpc_startable_flows())

    def start_flow_dynamic(self, flow_class_or_name, *args, **kwargs):
        """startFlowDynamic: only @StartableByRPC flows may be started
        (CordaRPCOpsImpl.startFlowDynamic); every permission decision is
        audited (FlowPermissionAuditEvent)."""
        requested = (flow_class_or_name if isinstance(flow_class_or_name, str)
                     else flow_name(flow_class_or_name))
        try:
            if isinstance(flow_class_or_name, str):
                flows = rpc_startable_flows()
                cls = flows.get(flow_class_or_name)
                if cls is None:
                    matches = [c for n, c in flows.items()
                               if n.rsplit(".", 1)[-1] == flow_class_or_name]
                    if len(matches) != 1:
                        raise FlowPermissionException(
                            f"Unknown or ambiguous flow {flow_class_or_name!r}")
                    cls = matches[0]
            else:
                cls = flow_class_or_name
                if not getattr(cls, "_startable_by_rpc", False):
                    raise FlowPermissionException(
                        f"{flow_name(cls)} is not annotated @StartableByRPC")
        except FlowPermissionException:
            self._audit_permission(requested, granted=False)
            raise
        self._audit_permission(requested, granted=True)
        flow: FlowLogic = cls(*args, **kwargs)
        return self.smm.add(flow)

    def _audit_permission(self, flow: str, granted: bool) -> None:
        audit = getattr(self.hub, "audit", None)
        if audit is not None:
            from .audit import FlowPermissionAuditEvent
            audit.record_audit_event(FlowPermissionAuditEvent(
                description="startFlowDynamic permission check",
                principal="rpc", flow_type=flow,
                permission_requested=f"StartFlow.{flow}",
                permission_granted=granted))

    def state_machines_snapshot(self) -> list[StateMachineInfo]:
        return [StateMachineInfo(fsm.run_id, flow_name(type(fsm.flow)), fsm.done)
                for fsm in self.smm.flows.values()]

    def state_machines_feed(self) -> DataFeed:
        def subscribe(cb):
            self.smm.changes.append(
                lambda event, fsm: cb((event, StateMachineInfo(
                    fsm.run_id, flow_name(type(fsm.flow)), fsm.done))))
        return DataFeed(self.state_machines_snapshot(), subscribe)

    def start_tracked_flow_dynamic(self, flow_class_or_name, *args, **kwargs):
        """startTrackedFlowDynamic (CordaRPCOps.kt:209): starts the flow AND
        returns (fsm, DataFeed) whose updates stream progress-tracker steps
        and the terminal ("removed", result-or-error) event."""
        subscribers: list = []
        buffered: list = []   # a fast flow can finish before anyone subscribes

        def emit(update):
            if not subscribers:
                buffered.append(update)
                return
            for cb in list(subscribers):
                try:
                    cb(update)
                except Exception:
                    pass

        def subscribe(cb):
            subscribers.append(cb)
            while buffered:
                cb(buffered.pop(0))

        fsm = self.start_flow_dynamic(flow_class_or_name, *args, **kwargs)
        tracker = getattr(fsm.flow, "progress_tracker", None)
        if tracker is not None:
            tracker.subscribe(
                lambda ev: emit(("progress", str(ev[2])))
                if ev[0] == "position" else None)

        def on_done(fut):
            try:
                emit(("removed", ["done", fut.result()]))
            except Exception as e:
                emit(("removed", ["failed", f"{type(e).__name__}: {e}"]))

        fsm.result_future.add_done_callback(on_done)
        return fsm, DataFeed(fsm.run_id, subscribe)

    def state_machine_recorded_transaction_mapping_snapshot(self) -> list:
        """stateMachineRecordedTransactionMapping (CordaRPCOps.kt:184-187):
        which flow recorded which transaction."""
        return [list(m) for m in self.smm.tx_mappings]

    def state_machine_recorded_transaction_mapping_feed(self) -> DataFeed:
        def subscribe(cb):
            self.smm.add_mapping_observer(lambda m: cb(list(m)))
        return DataFeed(
            self.state_machine_recorded_transaction_mapping_snapshot(),
            subscribe)

    # -- ledger --------------------------------------------------------------
    def verified_transactions_snapshot(self) -> list:
        return self.hub.storage.transactions

    def verified_transactions_feed(self) -> DataFeed:
        def subscribe(cb):
            self.hub.storage.add_commit_listener(cb)
        return DataFeed(self.hub.storage.transactions, subscribe)

    def network_map_feed(self) -> DataFeed:
        """networkMapFeed (CordaRPCOps.kt:193): snapshot + MapChange pushes."""
        def subscribe(cb):
            self.hub.network_map_cache.add_change_observer(cb)
        return DataFeed(self.network_map_snapshot(), subscribe)

    def wait_until_registered_with_network_map(self) -> bool:
        """waitUntilRegisteredWithNetworkMap (CordaRPCOps.kt:275) — here a
        non-blocking registration probe (the remote client polls it)."""
        return len(self.hub.network_map_cache.all_nodes()) > 1 or \
            self.hub.my_info in self.hub.network_map_cache.all_nodes()

    # -- vault ---------------------------------------------------------------
    def vault_snapshot(self, state_type: type | None = None) -> list:
        return self.hub.vault.unconsumed_states(state_type)

    def vault_query(self, state_type: type | None = None,
                    status: str = "unconsumed", **criteria) -> list:
        return self.hub.vault.query(state_type, status=status, **criteria)

    def vault_query_by(self, criteria=None, paging=None, sorting=None):
        """Full QueryCriteria query (reference CordaRPCOps.vaultQueryBy):
        returns a node.query.Page with states + total count."""
        return self.hub.vault.query_by(criteria, paging=paging, sorting=sorting)

    # -- monitoring ----------------------------------------------------------
    def metrics_snapshot(self) -> dict:
        """The node's metric registry (the JMX-export analog: verification
        timers/meters, batcher counters, flow rates), merged with the
        process-wide retry counters (utils.retry keeps its own registry —
        its call sites have no ServiceHub) so ``Retry.Attempts.*`` rides
        /metrics and /api/metrics alongside the node families."""
        from ..utils import retry
        merged = dict(retry.snapshot())
        merged.update(self.hub.monitoring.snapshot())
        return merged

    def health(self) -> dict:
        """Readiness payload for /readyz: named pass/fail checks plus the
        ``ready`` conjunction. Checks apply only where the capability
        exists — a host-only node is not held unready for cold device
        tables, a non-notary node not for raft state."""
        checks: dict = {}
        degraded: dict = {}
        controller_block: dict | None = None
        svc = self.hub.verifier_service
        batcher = getattr(svc, "batcher", None)
        if batcher is not None:
            # the dispatcher thread is the batcher's heart: if it died (or
            # close() ran), every queued Future would hang forever
            checks["batcher_dispatcher_alive"] = (
                batcher._thread.is_alive() and not batcher._closed)
            if batcher.use_device:
                # first-verify latency pays the multi-MB table transfer
                # unless the committed-table cache is already warm
                from ..ops.field import _DEVICE_TABLE_CACHE
                checks["device_tables_warm"] = bool(_DEVICE_TABLE_CACHE)
            status = getattr(batcher, "breaker_status", None)
            if status is not None:
                breakers = status()
                open_schemes = {name: st for name, st in breakers.items()
                                if st["state"] != "closed"}
                if open_schemes:
                    # DEGRADED, not unready: an open breaker means that
                    # scheme verifies on host — slower, still correct —
                    # so the node keeps taking traffic while operators
                    # see exactly which breaker tripped
                    degraded["device_breakers"] = open_schemes
        fleet_fn = getattr(svc, "fleet_status", None)
        if fleet_fn is not None:
            # out-of-process fleet: unready with NO workers attached (work
            # would queue forever); degraded — still serving — when fewer
            # than the configured fleet size are attached
            fleet = fleet_fn()
            checks["fleet_workers_attached"] = fleet["attached"] > 0
            if fleet.get("degraded"):
                degraded["fleet"] = {
                    "expected": fleet["expected"],
                    "attached": fleet["attached"],
                    "workers": sorted(fleet["workers"]),
                    # workers whose last load report is older than 3× the
                    # report interval: attached but possibly wedged
                    "stale": sorted(fleet.get("stale", ())),
                    "last_report_age_s": {
                        w: info.get("last_report_age_s")
                        for w, info in fleet["workers"].items()}}
            ctl = fleet.get("controller")
            if ctl is not None:
                # the FleetController's self-report: state, ladder rung,
                # recent actions — an operator hitting /readyz during an
                # episode sees exactly which concessions are in force
                controller_block = ctl
                if ctl.get("state") != "steady":
                    degraded["controller"] = {
                        "state": ctl["state"],
                        "ladder_step": ctl["ladder_step"],
                        "actions_total": ctl["actions_total"]}
        notary = getattr(self.hub, "notary_service", None)
        if notary is not None:
            raft = getattr(notary.uniqueness, "raft", None)
            if raft is not None:
                checks["raft_leader_known"] = raft.leader_id is not None
        else:
            # non-notary node: ready means it can REACH a notary
            checks["notary_known"] = bool(self.notary_identities())
        slo = getattr(self.hub, "slo_tracker", None)
        if slo is not None:
            # burn-rate alert = DEGRADED, not unready: the node still
            # commits, but it is eating its error budget — operators get
            # the per-objective budget/burn picture right on /readyz
            status = slo.status()
            if status["alerting"]:
                degraded["slo"] = status
        out = {"ready": all(checks.values()), "checks": checks}
        if controller_block is not None:
            out["controller"] = controller_block
        if degraded:
            out["degraded"] = degraded
        return out

    def profile_snapshot(self) -> dict:
        """The kernel flight recorder's full state (/debug/profile):
        per-kernel compile/dispatch/wait accounting, batch occupancy,
        prep/device overlap."""
        from ..observability import get_profiler
        return get_profiler().snapshot()

    def fleet_status(self) -> dict:
        """Verifier-fleet picture for /api/fleet (and tools/fleetstat.py):
        per-worker shard/capacity/queue-depth plus last-report freshness.
        Empty dict when the node runs an in-process verifier."""
        fleet_fn = getattr(self.hub.verifier_service, "fleet_status", None)
        return fleet_fn() if fleet_fn is not None else {}

    def request_timelines(self, limit: int | None = None) -> dict:
        """Per-request lifecycle event timelines for /debug/requests
        (submitted → routed → … → resolved), newest request first. Empty
        when the verifier keeps no request log (in-process path)."""
        log = getattr(self.hub.verifier_service, "request_log", None)
        return log.snapshot(limit=limit) if log is not None else {}

    def critpath_report(self, top_k: int = 10) -> dict:
        """Tail forensics for /debug/critpath: critical-path blame
        decomposition + top-K slowest transactions with annotated
        blocking chains, over every stitched trace currently in the
        tracer ring (observability/critpath.py). Cheap-empty when
        tracing is off."""
        from ..observability import critpath, get_tracer
        return critpath.critpath_report(get_tracer().traces(), top_k=top_k)

    def raft_report(self) -> dict:
        """Consensus observatory for /debug/raft: per-group raft
        introspection (leader, term, log length, election episodes,
        commit-path attribution percentiles) plus shard heat/skew when
        this node's notary shards its uniqueness provider. Empty-groups
        dict for a non-notary node — the endpoint is always safe."""
        from ..observability import consensus_obs
        groups: dict = {}
        sharded = None
        notary = getattr(self.hub, "notary_service", None)
        uniq = getattr(notary, "uniqueness", None) \
            if notary is not None else None
        if uniq is not None:
            shards = getattr(uniq, "shards", None)
            if shards:
                sharded = uniq
                for s, provider in enumerate(shards):
                    raft = getattr(provider, "raft", None)
                    if raft is not None:
                        groups[f"s{s}"] = [raft]
            else:
                raft = getattr(uniq, "raft", None)
                if raft is not None:
                    groups["s0"] = [raft]
        return consensus_obs.raft_report(groups, sharded=sharded)

    def timeseries_snapshot(self, names=None, limit: int | None = None,
                            since: float | None = None,
                            resolution: float | None = None) -> dict:
        """Retained time-series plane for /api/timeseries: downsampled
        multi-resolution history of the consensus gauges sampled by the
        raft pump (observability/timeseries.py). ``names`` filters to
        specific series, ``limit`` caps rows per resolution, ``since``
        drops buckets starting before that epoch time and ``resolution``
        keeps only the ring with that bucket width (the soak poller's
        incremental-fetch filters). Well-formed and empty when nothing
        has been recorded."""
        from ..observability import get_timeseries
        return get_timeseries().snapshot(names=names, limit=limit,
                                         since=since, resolution=resolution)

    def soak_report(self) -> dict:
        """Soak observatory for /debug/soak: every structure registered
        with the resource accounting plane — live size, declared kind
        (bounded vs grows-by-design), leak verdict over its retained
        ``Resource.*`` series — plus the subsystem CPU-attribution
        snapshot when a profiler is active (observability/soak.py).
        Well-formed and empty on a node with no registered probes."""
        from ..observability.soak import soak_report
        return soak_report()

    def vault_feed(self, state_type: type | None = None) -> DataFeed:
        def subscribe(cb):
            self.hub.vault.add_update_observer(cb)
        return DataFeed(self.vault_snapshot(state_type), subscribe)

    def vault_track_by(self, criteria=None, paging=None, sorting=None
                       ) -> DataFeed:
        """vaultTrackBy (CordaRPCOps.kt:137-156): criteria-filtered page
        snapshot + the vault update stream."""
        def subscribe(cb):
            self.hub.vault.add_update_observer(cb)
        return DataFeed(
            self.hub.vault.query_by(criteria, paging=paging, sorting=sorting),
            subscribe)

    def add_vault_transaction_note(self, tx_id, note: str) -> None:
        self.hub.vault.add_transaction_note(tx_id, note)

    def get_vault_transaction_notes(self, tx_id) -> list[str]:
        return self.hub.vault.get_transaction_notes(tx_id)

    def get_cash_balances(self) -> dict:
        """getCashBalances (CordaRPCOps.kt:230): unconsumed fungible-asset
        quantities summed per product (currency code)."""
        balances: dict = {}
        for sar in self.hub.vault.unconsumed_states():
            amount = getattr(sar.state.data, "amount", None)
            if amount is None:
                continue
            product = getattr(amount.token, "product", amount.token)
            key = str(product)
            balances[key] = balances.get(key, 0) + amount.quantity
        return balances

    # -- attachments ---------------------------------------------------------
    def upload_attachment(self, data: bytes):
        return self.hub.attachments.import_attachment(data)

    def open_attachment(self, att_id):
        return self.hub.attachments.open_attachment(att_id)

    def attachment_exists(self, att_id) -> bool:
        return self.hub.attachments.has_attachment(att_id)

    def upload_file(self, data_type: str, name: str | None,
                    data: bytes) -> str:
        """uploadFile (CordaRPCOps.kt:249): typed upload dispatch — files of
        type "attachment" land in attachment storage; other types go to any
        registered acceptor (the interest-rates-oracle fixes upload path)."""
        if data_type == "attachment":
            return str(self.hub.attachments.import_attachment(data))
        acceptor = getattr(self.hub, "file_uploaders", {}).get(data_type)
        if acceptor is None:
            raise ValueError(f"no acceptor for file type {data_type!r}")
        return acceptor(name, data)

    # -- contract upgrade authorisation --------------------------------------
    def authorise_contract_upgrade(self, state_and_ref,
                                   upgraded_contract_name: str) -> None:
        from ..flows.contract_upgrade import authorise_contract_upgrade
        authorise_contract_upgrade(self.hub, state_and_ref,
                                   upgraded_contract_name)

    def deauthorise_contract_upgrade(self, state_and_ref) -> None:
        from ..flows.contract_upgrade import deauthorise_contract_upgrade
        deauthorise_contract_upgrade(self.hub, state_and_ref)

    # -- identity ------------------------------------------------------------
    def party_from_key(self, key):
        return self.hub.identity_service.party_from_key(key)

    def well_known_party_from_x500_name(self, name):
        return self.hub.well_known_party(name)

    def parties_from_name(self, query: str, exact: bool = False) -> set:
        out = set()
        for info in self.hub.network_map_cache.all_nodes():
            name = str(info.legal_identity.name)
            if (exact and query == name) or (not exact and query in name):
                out.add(info.legal_identity)
        return out

    def party_from_name(self, name: str):
        """partyFromName (CordaRPCOps.kt:288): unique substring match."""
        matches = self.parties_from_name(name, exact=False)
        return next(iter(matches)) if len(matches) == 1 else None

    def node_identity_from_party(self, party):
        """nodeIdentityFromParty (CordaRPCOps.kt:313)."""
        for info in self.hub.network_map_cache.all_nodes():
            if info.legal_identity == party or \
                    info.legal_identity.owning_key == getattr(
                        party, "owning_key", None):
                return info
        return None

    # -- delegating aliases (the reference defines these as default methods
    # on CordaRPCOps itself: CordaRPCOps.kt:74,109-118,147-156,176,187,196) --
    def state_machines_and_updates(self):
        return self.state_machines_feed()

    def vault_and_updates(self):
        return self.vault_feed()

    def verified_transactions(self):
        return self.verified_transactions_feed()

    def state_machine_recorded_transaction_mapping(self):
        return self.state_machine_recorded_transaction_mapping_feed()

    def network_map_updates(self):
        return self.network_map_feed()

    @staticmethod
    def _typed_criteria(state_type):
        from .query import VaultQueryCriteria
        return (None if state_type is None
                else VaultQueryCriteria(contract_state_types=(state_type,)))

    def vault_query_by_criteria(self, criteria, state_type: type | None = None):
        typed = self._typed_criteria(state_type)
        if typed is not None:
            criteria = typed if criteria is None else (criteria & typed)
        return self.vault_query_by(criteria)

    def vault_query_by_with_paging_spec(self, criteria, paging):
        return self.vault_query_by(criteria, paging=paging)

    def vault_query_by_with_sorting(self, criteria, sorting):
        return self.vault_query_by(criteria, sorting=sorting)

    def vault_track(self, state_type: type | None = None):
        return self.vault_track_by(self._typed_criteria(state_type))

    def vault_track_by_criteria(self, criteria):
        return self.vault_track_by(criteria)

    def vault_track_by_with_paging_spec(self, criteria, paging):
        return self.vault_track_by(criteria, paging=paging)

    def vault_track_by_with_sorting(self, criteria, sorting):
        return self.vault_track_by(criteria, sorting=sorting)

    def party_from_x500_name(self, name):
        return self.well_known_party_from_x500_name(name)
