"""RPC operations surface — the client-visible node API.

Reference parity: CordaRPCOps (core/messaging/CordaRPCOps.kt:60-449, 54 ops)
and CordaRPCOpsImpl (node/internal/CordaRPCOpsImpl.kt:1-199). The wire
transport (queue-backed proxy with observable demux, RPCApi.kt/RPCServer.kt)
plugs in behind this object; in-process callers (shell, tests, webserver
equivalent) call it directly.

Streaming (`DataFeed`) follows the reference shape: a snapshot plus a
subscription handle; updates are delivered to registered callbacks (the Rx
Observable analog on the deterministic host runtime).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

from ..flows.api import FlowLogic, rpc_startable_flows, flow_name


@dataclass
class DataFeed:
    """snapshot + live updates (CordaRPCOps DataFeed)."""

    snapshot: Any
    _subscribe: Callable[[Callable], None]

    def subscribe(self, callback: Callable) -> None:
        self._subscribe(callback)


@dataclass(frozen=True)
class StateMachineInfo:
    run_id: str
    flow_class: str
    done: bool


from ..core.serialization import register_type as _register_type  # noqa: E402

_register_type("rpc.StateMachineInfo", StateMachineInfo)


class FlowPermissionException(Exception):
    pass


class CordaRPCOps:
    """The operation set served to clients (CordaRPCOps.kt:60+)."""

    def __init__(self, hub, smm):
        self.hub = hub
        self.smm = smm

    # -- node / network info -------------------------------------------------
    def node_identity(self):
        return self.hub.my_info

    def network_map_snapshot(self) -> list:
        return self.hub.network_map_cache.all_nodes()

    def notary_identities(self) -> list:
        return [n.notary_identity for n in self.hub.network_map_cache.notary_nodes()]

    def current_node_time(self):
        import datetime
        return datetime.datetime.now(datetime.timezone.utc)

    # -- flows ---------------------------------------------------------------
    def registered_flows(self) -> list[str]:
        return sorted(rpc_startable_flows())

    def start_flow_dynamic(self, flow_class_or_name, *args, **kwargs):
        """startFlowDynamic: only @StartableByRPC flows may be started
        (CordaRPCOpsImpl.startFlowDynamic); every permission decision is
        audited (FlowPermissionAuditEvent)."""
        requested = (flow_class_or_name if isinstance(flow_class_or_name, str)
                     else flow_name(flow_class_or_name))
        try:
            if isinstance(flow_class_or_name, str):
                flows = rpc_startable_flows()
                cls = flows.get(flow_class_or_name)
                if cls is None:
                    matches = [c for n, c in flows.items()
                               if n.rsplit(".", 1)[-1] == flow_class_or_name]
                    if len(matches) != 1:
                        raise FlowPermissionException(
                            f"Unknown or ambiguous flow {flow_class_or_name!r}")
                    cls = matches[0]
            else:
                cls = flow_class_or_name
                if not getattr(cls, "_startable_by_rpc", False):
                    raise FlowPermissionException(
                        f"{flow_name(cls)} is not annotated @StartableByRPC")
        except FlowPermissionException:
            self._audit_permission(requested, granted=False)
            raise
        self._audit_permission(requested, granted=True)
        flow: FlowLogic = cls(*args, **kwargs)
        return self.smm.add(flow)

    def _audit_permission(self, flow: str, granted: bool) -> None:
        audit = getattr(self.hub, "audit", None)
        if audit is not None:
            from .audit import FlowPermissionAuditEvent
            audit.record_audit_event(FlowPermissionAuditEvent(
                description="startFlowDynamic permission check",
                principal="rpc", flow_type=flow,
                permission_requested=f"StartFlow.{flow}",
                permission_granted=granted))

    def state_machines_snapshot(self) -> list[StateMachineInfo]:
        return [StateMachineInfo(fsm.run_id, flow_name(type(fsm.flow)), fsm.done)
                for fsm in self.smm.flows.values()]

    def state_machines_feed(self) -> DataFeed:
        def subscribe(cb):
            self.smm.changes.append(
                lambda event, fsm: cb((event, StateMachineInfo(
                    fsm.run_id, flow_name(type(fsm.flow)), fsm.done))))
        return DataFeed(self.state_machines_snapshot(), subscribe)

    # -- ledger --------------------------------------------------------------
    def verified_transactions_snapshot(self) -> list:
        return self.hub.storage.transactions

    def verified_transactions_feed(self) -> DataFeed:
        def subscribe(cb):
            self.hub.storage.add_commit_listener(cb)
        return DataFeed(self.hub.storage.transactions, subscribe)

    # -- vault ---------------------------------------------------------------
    def vault_snapshot(self, state_type: type | None = None) -> list:
        return self.hub.vault.unconsumed_states(state_type)

    def vault_query(self, state_type: type | None = None,
                    status: str = "unconsumed", **criteria) -> list:
        return self.hub.vault.query(state_type, status=status, **criteria)

    def vault_query_by(self, criteria=None, paging=None, sorting=None):
        """Full QueryCriteria query (reference CordaRPCOps.vaultQueryBy):
        returns a node.query.Page with states + total count."""
        return self.hub.vault.query_by(criteria, paging=paging, sorting=sorting)

    # -- monitoring ----------------------------------------------------------
    def metrics_snapshot(self) -> dict:
        """The node's metric registry (the JMX-export analog: verification
        timers/meters, batcher counters, flow rates)."""
        return self.hub.monitoring.snapshot()

    def vault_feed(self, state_type: type | None = None) -> DataFeed:
        def subscribe(cb):
            self.hub.vault.add_update_observer(cb)
        return DataFeed(self.vault_snapshot(state_type), subscribe)

    # -- attachments ---------------------------------------------------------
    def upload_attachment(self, data: bytes):
        return self.hub.attachments.import_attachment(data)

    def open_attachment(self, att_id):
        return self.hub.attachments.open_attachment(att_id)

    def attachment_exists(self, att_id) -> bool:
        return self.hub.attachments.has_attachment(att_id)

    # -- identity ------------------------------------------------------------
    def party_from_key(self, key):
        return self.hub.identity_service.party_from_key(key)

    def well_known_party_from_x500_name(self, name):
        return self.hub.well_known_party(name)

    def parties_from_name(self, query: str, exact: bool = False) -> set:
        out = set()
        for info in self.hub.network_map_cache.all_nodes():
            name = str(info.legal_identity.name)
            if (exact and query == name) or (not exact and query in name):
                out.add(info.legal_identity)
        return out
