"""ServiceHub: the service locator every flow and node component sees.

Reference parity: ServiceHub (core/node/ServiceHub.kt), NodeInfo,
TransactionStorage (Services.kt / storage SPI), NetworkMapCache lookups.
The hub composes: messaging, validated-tx storage, identity, key management,
attachments, the verifier service, and (when started) the state machine.
"""
from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass

from ..core.contracts.structures import Attachment
from ..core.crypto.keys import KeyPair, PublicKey
from ..core.crypto.secure_hash import SecureHash
from ..core.crypto.signatures import Crypto, DigitalSignatureWithKey
from ..core.identity import Party


class InMemoryAttachmentStorage:
    """Content-addressed attachment store (NodeAttachmentService semantics:
    import returns the hash id; open verifies by construction since the id IS
    the hash — NodeAttachmentService.kt:35,148)."""

    def __init__(self):
        self._blobs: dict[SecureHash, bytes] = {}

    def import_attachment(self, data: bytes) -> SecureHash:
        att_id = SecureHash.sha256(data)
        self._blobs.setdefault(att_id, bytes(data))
        return att_id

    def open_attachment(self, att_id: SecureHash) -> Attachment | None:
        data = self._blobs.get(att_id)
        return Attachment(att_id, data) if data is not None else None

    def has_attachment(self, att_id: SecureHash) -> bool:
        return att_id in self._blobs


class InMemoryIdentityService:
    """key → Party resolution, including verified anonymous identities
    (InMemoryIdentityService.kt:1-162: registerAnonymousIdentity with
    ownership proof, partyFromAnonymous)."""

    def __init__(self, parties=()):
        self._by_key: dict[PublicKey, Party] = {}
        self._anonymous: dict[PublicKey, Party] = {}
        for p in parties:
            self.register(p)

    def register(self, party: Party) -> None:
        self._by_key[party.owning_key] = party

    def party_from_key(self, key: PublicKey) -> Party | None:
        return self._by_key.get(key) or self._anonymous.get(key)

    def parties_from_keys(self, keys) -> tuple[Party, ...]:
        return tuple(p for p in (self.party_from_key(k) for k in keys)
                     if p is not None)

    # -- confidential identities --------------------------------------------
    @staticmethod
    def ownership_content(anonymous_key: PublicKey, owner_name) -> bytes:
        """The canonical bytes a well-known identity signs to attest it owns
        an anonymous key (the certificate-path role of the reference's
        registerAnonymousIdentity, X.509 replaced by the canonical codec)."""
        from ..core.serialization import serialize
        return serialize(["confidential-identity", anonymous_key,
                          str(owner_name)])

    def verify_and_register_anonymous(self, anonymous, well_known: Party,
                                      signature: bytes) -> None:
        """Validate the ownership attestation and record the mapping;
        raises on a bad signature (registerAnonymousIdentity semantics)."""
        from ..core.crypto.signatures import DigitalSignatureWithKey
        content = self.ownership_content(anonymous.owning_key, well_known.name)
        DigitalSignatureWithKey(signature, well_known.owning_key).verify(content)
        self._anonymous[anonymous.owning_key] = well_known

    def well_known_party_from_anonymous(self, party) -> Party | None:
        """partyFromAnonymous: resolve an AnonymousParty (or pass a Party
        through) to its verified well-known identity."""
        if isinstance(party, Party):
            return party
        return self._anonymous.get(party.owning_key)


@dataclass(frozen=True)
class ServiceInfo:
    """An advertised service (notary etc.) — ServiceInfo/ServiceType analog."""

    type: str           # e.g. "corda.notary.simple", "corda.notary.validating"
    name: str | None = None


@dataclass(frozen=True)
class NodeInfo:
    """Directory entry for a node (core NodeInfo: address + identity +
    advertised services)."""

    address: str
    legal_identity: Party
    advertised_services: tuple[ServiceInfo, ...] = ()

    @property
    def notary_identity(self) -> Party:
        return self.legal_identity


class TransactionStorage:
    """Validated-transaction store with commit listeners
    (DBTransactionStorage + its Rx `updates` feed analog)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._txs: dict = {}
        self._listeners: list = []

    def add_transaction(self, stx, notify: bool = True) -> bool:
        with self._lock:
            fresh = stx.id not in self._txs
            if fresh:
                self._txs[stx.id] = stx
        if fresh and notify:
            self.notify_listeners(stx)
        return fresh

    def notify_listeners(self, stx) -> None:
        with self._lock:
            listeners = list(self._listeners)
        for cb in listeners:
            cb(stx)

    def get_transaction(self, tx_id):
        with self._lock:
            return self._txs.get(tx_id)

    def add_commit_listener(self, cb) -> None:
        with self._lock:
            self._listeners.append(cb)

    @property
    def transactions(self) -> list:
        with self._lock:
            return list(self._txs.values())


class DurableTransactionStorage(TransactionStorage):
    """Validated-tx store persisted on the kvlog engine (DBTransactionStorage
    role): canonical-codec blobs keyed by tx id, replayed at open."""

    def __init__(self, path: str, use_native: bool | None = None):
        super().__init__()
        from ..core.serialization import deserialize, serialize
        from ..storage import KvStore
        self._serialize = serialize
        self._kv = KvStore(path, use_native=use_native)
        for key, blob in self._kv.items():
            stx = deserialize(blob)
            self._txs[stx.id] = stx

    def add_transaction(self, stx, notify: bool = True) -> bool:
        with self._lock:
            fresh = stx.id not in self._txs
            if fresh:
                self._kv[stx.id.bytes] = self._serialize(stx)
                self._txs[stx.id] = stx
        if fresh and notify:
            self.notify_listeners(stx)
        return fresh

    def close(self) -> None:
        self._kv.close()


class KeyManagementService:
    """Signing keys + fresh-key generation
    (PersistentKeyManagementService / E2ETestKeyManagementService analog).

    ``store_path`` makes fresh (confidential-identity) keys DURABLE: each
    generated/added pair is appended to the store and reloaded on
    construction — without it a restarted node would filter its own
    fresh-key-owned vault states out as irrelevant (review r3)."""

    def __init__(self, key_pairs=(), store_path: str | None = None):
        self._keys: dict[PublicKey, KeyPair] = {kp.public: kp for kp in key_pairs}
        self._store_path = store_path
        if store_path is not None and os.path.exists(store_path):
            from ..core.crypto.keys import PrivateKey
            from ..core.crypto.schemes import scheme_by_id
            with open(store_path) as f:
                for line in f:
                    if not line.strip():
                        continue
                    sid, priv_hex, pub_hex = json.loads(line)
                    scheme = scheme_by_id(sid)
                    kp = KeyPair(PublicKey(scheme, bytes.fromhex(pub_hex)),
                                 PrivateKey(scheme, bytes.fromhex(priv_hex)))
                    self._keys[kp.public] = kp

    def _persist(self, kp: KeyPair) -> None:
        if self._store_path is None:
            return
        with open(self._store_path, "a") as f:
            f.write(json.dumps([kp.public.scheme.scheme_number_id,
                                kp.private.encoded.hex(),
                                kp.public.encoded.hex()]) + "\n")
            f.flush()
            os.fsync(f.fileno())

    @property
    def keys(self) -> set[PublicKey]:
        return set(self._keys)

    def fresh_key(self, scheme=None) -> KeyPair:
        from ..core.crypto.keys import generate_keypair
        from ..core.crypto.schemes import DEFAULT_SIGNATURE_SCHEME
        kp = generate_keypair(scheme or DEFAULT_SIGNATURE_SCHEME)
        self._keys[kp.public] = kp
        self._persist(kp)
        return kp

    def add(self, kp: KeyPair) -> None:
        if kp.public not in self._keys:
            self._persist(kp)
        self._keys[kp.public] = kp

    def key_pair(self, key: PublicKey) -> KeyPair:
        kp = self._keys.get(key)
        if kp is None:
            raise ValueError(f"No private key known for {key.to_string_short()}")
        return kp

    def sign(self, content: bytes, key: PublicKey) -> DigitalSignatureWithKey:
        return Crypto.sign_with_key(self.key_pair(key), content)


class NetworkMapCache:
    """name → NodeInfo directory (InMemoryNetworkMapCache analog; fed by the
    network-map service or statically by MockNetwork)."""

    def __init__(self):
        self._nodes: dict[str, NodeInfo] = {}
        self._observers: list = []    # cb(("added"|"removed", NodeInfo))

    def add_node(self, info: NodeInfo) -> None:
        self._nodes[str(info.legal_identity.name)] = info
        self._emit(("added", info))

    def remove_node(self, name: str) -> None:
        info = self._nodes.pop(name, None)
        if info is not None:
            self._emit(("removed", info))

    def add_change_observer(self, cb) -> None:
        """networkMapFeed's MapChange stream (NetworkMapCache.kt:1-134)."""
        self._observers.append(cb)

    def _emit(self, change) -> None:
        for cb in list(self._observers):
            try:
                cb(change)
            except Exception:
                pass

    def get_node_by_legal_name(self, name: str) -> NodeInfo | None:
        return self._nodes.get(str(name))

    def party_from_name(self, name: str) -> Party | None:
        info = self._nodes.get(str(name))
        return info.legal_identity if info else None

    def notary_nodes(self) -> list[NodeInfo]:
        return [n for n in self._nodes.values()
                if any(s.type.startswith("corda.notary") for s in n.advertised_services)]

    def all_nodes(self) -> list[NodeInfo]:
        return list(self._nodes.values())


class ServiceHub:
    """The hub handed to flows (`flow.service_hub`) and services."""

    def __init__(self, my_info: NodeInfo, network_service,
                 key_pairs=(), verifier_service=None):
        from ..observability import get_profiler, get_tracer
        from ..utils.metrics import MetricRegistry
        self.my_info = my_info
        self.network_service = network_service
        # the node-wide metric registry (MonitoringService.kt:11 parity);
        # the verifier service and SMM publish into it, /metrics exports it
        self.monitoring = MetricRegistry()
        # span-ring accounting: how many spans the bounded ring has evicted
        # (a scraper seeing this grow knows /traces is lossy right now) and
        # how many it holds. Read through get_tracer per call so
        # enable/disable_tracing swaps take effect; the no-op tracer has no
        # ring → both read 0.
        self.monitoring.gauge(
            "Tracing.SpansDropped",
            lambda: getattr(getattr(get_tracer(), "ring", None),
                            "dropped", 0) or 0)
        self.monitoring.gauge(
            "Tracing.SpansBuffered",
            lambda: len(getattr(get_tracer(), "ring", None) or ()))
        # resource accounting plane (soak observatory): the span ring and
        # its cumulative drop counter register size probes with the
        # process-global registry, so any sampler (harness soak observer,
        # an operator scraping /debug/soak) gets their leak verdicts and
        # the windowed drop RATE for free. Registration is by-name
        # idempotent — a fleet of hubs in one process re-registers the
        # same process-wide structures harmlessly.
        from ..observability.resprof import get_resources, process_rss_bytes
        _resources = get_resources()
        _resources.register(
            "Tracing.SpanRing",
            lambda: len(getattr(get_tracer(), "ring", None) or ()),
            kind="bounded")
        _resources.register(
            "Tracing.SpansDropped",
            lambda: getattr(getattr(get_tracer(), "ring", None),
                            "dropped", 0) or 0,
            kind="grows", rate=True)
        _resources.register("Process.RSSBytes", process_rss_bytes,
                            kind="grows")
        # kernel flight recorder (observability/profiling): compile/
        # occupancy/overlap gauges + the shared dispatch histograms
        get_profiler().publish(self.monitoring)
        # set by NotaryService.__init__ on notary nodes; the readiness
        # probe checks its commit-log backend
        self.notary_service = None
        # optional observability/slo.SLOTracker — /readyz surfaces its
        # burn-rate alerts as degraded.slo (set by the ledger harness or
        # an operator wiring SLOs onto a node)
        self.slo_tracker = None
        from .audit import InMemoryAuditService
        self.audit = InMemoryAuditService()
        self.storage = TransactionStorage()
        self.key_management = KeyManagementService(key_pairs)
        self.identity_service = InMemoryIdentityService([my_info.legal_identity])
        self.attachments = InMemoryAttachmentStorage()
        self.network_map_cache = NetworkMapCache()
        self.network_map_cache.add_node(my_info)
        self.verifier_service = verifier_service
        self.smm = None  # set by the node after SMM construction
        from .vault import NodeVaultService
        self.vault = NodeVaultService(self)
        # typed projections of vault states into custom schema tables
        # (NodeSchemaService + HibernateObserver role; node/schemas.py)
        from .schemas import SchemaService
        self.schema_service = SchemaService(self).start()

    # -- identity / directory -----------------------------------------------
    def well_known_party(self, name) -> Party | None:
        return self.network_map_cache.party_from_name(name)

    # -- state resolution (WireTransaction.toLedgerTransaction seam) ---------
    def load_state(self, ref):
        stx = self.storage.get_transaction(ref.txhash)
        if stx is None:
            return None
        wtx = stx.tx if hasattr(stx, "tx") else stx
        if ref.index >= len(wtx.outputs):
            return None
        return wtx.outputs[ref.index]

    # -- verification (the TransactionVerifierService seam) ------------------
    def verify_transaction(self, stx,
                           check_sufficient_signatures: bool = True) -> None:
        """BLOCKING verify through the node's configured
        TransactionVerifierService (Services.kt:544-550) — for callers that
        may block their thread (RPC handlers, tests, tools). Flows do NOT
        call this: they `yield flows.api.Verify(stx)` and the SMM parks them
        on the service future (the reference's fiber suspension,
        FlowStateMachineImpl.kt:379-393), which is what lets Tpu/OutOfProcess
        backends batch across concurrently-suspended flows."""
        svc = self.verifier_service
        # ONLY services whose futures resolve OFF the node thread may be
        # blocked on here: e.g. the OutOfProcess service's responses arrive
        # on the node's SerialExecutor — a caller ON that executor blocking
        # for them would deadlock. (The flow path has no such restriction:
        # Verify parks instead of blocking.)
        if svc is not None and hasattr(svc, "verify_signed") and \
                getattr(svc, "resolves_off_node_thread", False):
            svc.verify_signed(
                stx, self,
                check_sufficient_signatures=check_sufficient_signatures
            ).result()
            return
        stx.verify(self, check_sufficient_signatures=check_sufficient_signatures)

    # -- ledger recording (ServiceHub.recordTransactions) --------------------
    def record_transactions(self, *stxs) -> None:
        import time as _time

        from ..observability import get_tracer
        # vault updates land before ledger-commit waiters wake, so a resumed
        # flow observes a consistent vault (HibernateObserver ordering analog)
        fresh = [stx for stx in stxs
                 if self.storage.add_transaction(stx, notify=False)]
        if fresh:
            smm = getattr(self, "smm", None)
            fsm = smm.current_fsm if smm is not None else None
            ctx = getattr(fsm, "trace_ctx", None)
            # vault.update: the last commit-path stage — consumed/produced
            # bookkeeping plus observer fan-out, under the recording flow's
            # trace so /traces shows flow.run → ... → vault.update whole
            with get_tracer().span("vault.update", parent=ctx,
                                   n_txs=len(fresh)) as sp:
                t0 = _time.perf_counter()
                try:
                    self.vault.notify_all(fresh)
                    for stx in fresh:
                        self.storage.notify_listeners(stx)
                finally:
                    trace_id = getattr(sp.context() or ctx, "trace_id", None)
                    self.monitoring.histogram("vault_update_seconds").update(
                        _time.perf_counter() - t0, trace_id=trace_id)
            # flow → transaction mapping for the RPC mapping feed
            # (StateMachineRecordedTransactionMapping)
            if smm is not None and fsm is not None:
                for stx in fresh:
                    smm.record_tx_mapping(fsm.run_id, stx.id)

    # -- signing -------------------------------------------------------------
    def sign(self, content: bytes, key: PublicKey | None = None
             ) -> DigitalSignatureWithKey:
        key = key or self.my_info.legal_identity.owning_key
        return self.key_management.sign(content, key)

    def sign_initial_transaction(self, wtx, key: PublicKey | None = None):
        from ..core.transactions.signed import SignedTransaction
        key = key or self.my_info.legal_identity.owning_key
        return SignedTransaction.of(wtx, [self.sign(wtx.id.bytes, key)])
