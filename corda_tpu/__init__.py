"""corda_tpu — a TPU-native distributed-ledger framework.

Capabilities of Corda (reference survey: SURVEY.md), architecture of JAX/XLA:

- ``corda_tpu.core``     — ledger algebra, crypto, transactions, serialization, flows API
- ``corda_tpu.ops``      — JAX/Pallas device kernels (SHA-256, Ed25519, secp256k1, Merkle)
- ``corda_tpu.parallel`` — device-mesh sharding and multi-chip fan-out
- ``corda_tpu.node``     — node runtime (state machine, messaging, services, notaries)
- ``corda_tpu.models``   — contract/flow "model families" (finance CorDapps, demos)
- ``corda_tpu.verifier`` — standalone verification worker
- ``corda_tpu.testing``  — MockNetwork, ledger DSL, driver
"""

__version__ = "0.1.0"
